"""North-star benchmark: BASELINE config 5 plus the steady-state churn
scenario (config 6) on the sim control plane.

Delegates to tpukube.sim.scenarios — the SAME code paths the acceptance
tests (tests/test_config5.py, tests/test_config6.py) and `tpukube-sim
5|6` run — and prints one JSON line. Headline metric: config 5's cluster
utilization vs the BASELINE.json >= 95% target; the line also carries
the gang-commit p50, the churn scenario's utilization-stability and
re-schedule numbers (the release loop's workload), and — new with the
obs layer — a ``phases`` key with per-phase timeline stats (p50/p99/max
ms per scheduling phase, from the run's own decision trace) so N-run
spread can be attributed to a phase, not just observed. Every
pre-existing key is unchanged; the ``lint`` key (ISSUE 3) tracks
tpukube-lint's wall time over the tree and pins the instrumented-lock
mode off for the measured runs.
"""

from __future__ import annotations

import json
import time


def process_stats() -> dict:
    """Control-plane process overhead for the bench line: peak RSS and
    CPU time, so BENCH_*.json tracks scheduler cost across PRs, not
    just scheduler speed."""
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    return {
        # ru_maxrss is KiB on Linux
        "peak_rss_bytes": int(ru.ru_maxrss) * 1024,
        "cpu_user_s": round(ru.ru_utime, 2),
        "cpu_system_s": round(ru.ru_stime, 2),
    }


def lint_stats() -> dict:
    """tpukube-lint wall time over the real tree, tracked per PR like
    the scheduler numbers: the static passes run on every tier-1
    invocation, so their cost is part of the dev-loop budget. Also
    records that the instrumented-lock mode is off (the scenario-5 /
    churn numbers above are measured with raw, unproxied locks — the
    zero-overhead default tests/test_lint.py asserts)."""
    import os

    from tpukube.analysis import run_all

    tree = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tpukube")
    t0 = time.perf_counter()
    findings = run_all([tree])
    return {
        "wall_s": round(time.perf_counter() - t0, 3),
        "findings": len(findings),
        "lock_monitor": False,
    }


def run() -> dict:
    from tpukube.sim import scenarios

    t0 = time.perf_counter()
    result = scenarios.multi_tenant_northstar(None)
    result["sched_wall_s"] = round(time.perf_counter() - t0, 2)
    c = scenarios.churn(None)
    result["churn"] = {
        k: c[k] for k in (
            "util_min_after_refill_percent", "resched_p50_s",
            "resched_p99_s", "waves", "wave_size", "lifecycle_releases",
            # per-phase timeline stats for the churn run too: re-schedule
            # spread attributed to a phase, not just observed
            "phases",
        ) if k in c
    }
    result["process"] = process_stats()
    result["lint"] = lint_stats()
    result["chaos"] = chaos_stats()
    return result


def chaos_stats() -> dict:
    """Chaos-scenario cost tracking (ISSUE 4): wall time of the seeded
    apiserver-chaos run (scenario 8) and of the crash-recovery run
    (scenario 9), plus the recovery latency proper (extender crash ->
    ledger converged). Tracked per PR like the scheduler numbers so a
    regression in retry/rebuild cost shows up in BENCH_*.json."""
    from tpukube.sim import scenarios

    s8 = scenarios.run(8)
    s9 = scenarios.run(9)
    return {
        "scenario8_wall_s": s8["wall_s"],
        "scenario8_faults_injected": s8["faults"]["injected"],
        "scenario9_wall_s": s9["wall_s"],
        "recovery_s": s9["recovery_s"],
    }


if __name__ == "__main__":
    print(json.dumps(run()))

"""North-star benchmark: BASELINE config 5 plus the steady-state churn
scenario (config 6) on the sim control plane.

Delegates to tpukube.sim.scenarios — the SAME code paths the acceptance
tests (tests/test_config5.py, tests/test_config6.py) and `tpukube-sim
5|6` run — and prints one JSON line. Headline metric: config 5's cluster
utilization vs the BASELINE.json >= 95% target; the line also carries
the gang-commit p50, the churn scenario's utilization-stability and
re-schedule numbers (the release loop's workload), and — new with the
obs layer — a ``phases`` key with per-phase timeline stats (p50/p99/max
ms per scheduling phase, from the run's own decision trace) so N-run
spread can be attributed to a phase, not just observed. Every
pre-existing key is unchanged; the ``lint`` key (ISSUE 3) tracks
tpukube-lint's wall time over the tree and pins the instrumented-lock
mode off for the measured runs.
"""

from __future__ import annotations

import json
import logging
import time


def process_stats() -> dict:
    """Control-plane process overhead for the bench line: peak RSS and
    CPU time, so BENCH_*.json tracks scheduler cost across PRs, not
    just scheduler speed."""
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    return {
        # ru_maxrss is KiB on Linux
        "peak_rss_bytes": int(ru.ru_maxrss) * 1024,
        "cpu_user_s": round(ru.ru_utime, 2),
        "cpu_system_s": round(ru.ru_stime, 2),
    }


def lint_stats() -> dict:
    """tpukube-lint wall time over the real tree, tracked per PR like
    the scheduler numbers: the static passes run on every tier-1
    invocation, so their cost is part of the dev-loop budget. Also
    records that the instrumented-lock mode is off (the scenario-5 /
    churn numbers above are measured with raw, unproxied locks — the
    zero-overhead default tests/test_lint.py asserts)."""
    import os

    from tpukube.analysis import run_all

    tree = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tpukube")
    t0 = time.perf_counter()
    findings = run_all([tree])
    return {
        "wall_s": round(time.perf_counter() - t0, 3),
        "findings": len(findings),
        "lock_monitor": False,
    }


def sched_micro() -> dict:
    """Filter/prioritize/plan microbench on a 16x16x16 synthetic mesh
    (4096 chips, 64 nodes) — the ISSUE 5 acceptance number. Measures
    the p50 webhook wall with the epoch-cached scheduling snapshot hot
    (steady state: no mutations between cycles) AND with the cache
    invalidated before every call (the pre-snapshot per-webhook rebuild
    behavior), so the recorded speedup is the cache's real win. The
    ``plan`` row times a full 64-chip gang placement search including
    its sweep build — the per-reservation cost the vectorized sweep
    bounds. tools/check.sh's perf smoke stage fails on >1.5x regression
    of the p50s vs the committed tools/perf_floor.json."""
    from tpukube.core import codec
    from tpukube.core.config import load_config
    from tpukube.core.mesh import MeshSpec
    from tpukube.core.types import (
        RESOURCE_TPU,
        AllocResult,
        ChipInfo,
        ContainerInfo,
        NodeInfo,
        PodInfo,
        ResourceList,
        make_device_id,
    )
    from tpukube.sched import slicefit
    from tpukube.sched.extender import Extender

    cfg = load_config(env={})
    mesh = MeshSpec(dims=(16, 16, 16), host_block=(4, 4, 4))
    ext = Extender(cfg)
    hosts = mesh.all_hosts()
    for host in hosts:
        chips = [
            ChipInfo(chip_id=f"{host}-chip-{i}", index=i, coord=c,
                     hbm_bytes=cfg.hbm_bytes_per_chip,
                     num_cores=cfg.cores_per_chip)
            for i, c in enumerate(mesh.coords_of_host(host))
        ]
        info = NodeInfo(name=host, chips=chips, slice_id=cfg.slice_id)
        ext.state.upsert_node(host, codec.annotate_node(info, mesh))
    # structured load: a third of the hosts fully occupied (existing
    # jobs), so the sweep has real walls to pack against
    for n, host in enumerate(hosts[: len(hosts) // 3]):
        ext.state.commit(AllocResult(
            pod_key=f"default/occ-{n}", node_name=host,
            device_ids=[make_device_id(i)
                        for i in range(mesh.chips_per_host)],
            coords=mesh.coords_of_host(host),
        ))
    # listified: `names` doubles as the wire body's NodeNames below,
    # and the wire carries a JSON array (node_names() itself serves a
    # cached tuple since ISSUE 11)
    names = list(ext.state.node_names())
    pod = PodInfo(name="micro-probe", containers=[
        ContainerInfo(name="main",
                      requests=ResourceList({RESOURCE_TPU: 1})),
    ])
    occupied = ext.state.occupied_coords(cfg.slice_id)

    def p50_ms(fn, n: int = 25) -> float:
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        times.sort()
        return round(1000 * times[len(times) // 2], 3)

    def run_filter():
        ext.filter(pod, node_names=names)

    def run_prioritize():
        ext.prioritize(pod, node_names=names)

    def run_plan():
        # full gang placement search incl. its own sweep build (the
        # cold per-reservation cost; reservation cycles proper reuse
        # the snapshot's cached sweep)
        slicefit.find_slice(mesh, occupied, count=64)

    run_filter(), run_prioritize(), run_plan()  # warm the cache
    rebuilds0, hits0 = ext.snapshots.rebuilds, ext.snapshots.hits
    out = {
        "mesh": list(mesh.dims),
        "nodes": len(names),
        "filter_p50_ms": p50_ms(run_filter),
        "prioritize_p50_ms": p50_ms(run_prioritize),
        "plan_p50_ms": p50_ms(run_plan),
    }
    hits = ext.snapshots.hits - hits0
    rebuilds = ext.snapshots.rebuilds - rebuilds0
    out["snapshot_hit_rate"] = round(
        hits / (hits + rebuilds), 4) if hits + rebuilds else None
    # the same webhooks with the snapshot cache defeated (rebuild per
    # call — the pre-ISSUE-5 behavior): the recorded speedup is the
    # acceptance's >=2x
    def nocache(fn):
        def run():
            ext.snapshots.invalidate()
            fn()
        return run

    out["filter_nocache_p50_ms"] = p50_ms(nocache(run_filter))
    out["prioritize_nocache_p50_ms"] = p50_ms(nocache(run_prioritize))
    out["filter_speedup"] = round(
        out["filter_nocache_p50_ms"] / out["filter_p50_ms"], 2)
    out["prioritize_speedup"] = round(
        out["prioritize_nocache_p50_ms"] / out["prioritize_p50_ms"], 2)
    # ISSUE 10: the snapshot-maintenance microbench — after a mutation,
    # advancing the cached snapshot via the O(Δ) delta path vs the
    # forced full O(chips) rebuild (invalidate drops the base). The
    # acceptance floor (perf_floor.json min_speedup) is >= 5x.
    probe_host = hosts[-1]

    def mutate():
        ext.state.commit(AllocResult(
            pod_key="default/delta-probe", node_name=probe_host,
            device_ids=[make_device_id(0)],
            coords=[mesh.coords_of_host(probe_host)[0]],
        ))
        ext.state.release("default/delta-probe")

    def run_delta():
        mutate()
        ext.snapshots.current()

    def run_forced_rebuild():
        mutate()
        ext.snapshots.invalidate()
        ext.snapshots.current()

    run_delta()  # warm
    out["snapshot_delta_p50_ms"] = p50_ms(run_delta)
    out["snapshot_rebuild_p50_ms"] = p50_ms(run_forced_rebuild)
    out["snapshot_delta_speedup"] = round(
        out["snapshot_rebuild_p50_ms"]
        / max(out["snapshot_delta_p50_ms"], 1e-6), 2)
    # ISSUE 8 satellite: the same /filter webhook through the FULL
    # dispatch (handle(): parse + decision lock + trace record) both
    # in-process and over real HTTP, so the recorded numbers separate
    # scheduling compute from socket/JSON-transport overhead — the
    # split that motivated batching (BENCH r01-r05's residual
    # sched_wall_s was HTTP-dominated once PR 5 killed the compute).
    import http.client

    from tpukube.sched.extender import make_app
    from tpukube.sim.harness import _AppThread, _free_port

    pod_obj = {
        "metadata": {"name": "micro-probe", "namespace": "default",
                     "uid": "uid-micro-probe", "annotations": {},
                     "labels": {}},
        "spec": {"priority": 0, "containers": [{
            "name": "main",
            "resources": {"requests": {RESOURCE_TPU: "1"}},
        }]},
    }
    body = {"Pod": pod_obj, "NodeNames": names}

    def run_inproc():
        ext.handle("filter", body)

    run_inproc()  # warm
    out["filter_inproc_p50_ms"] = p50_ms(run_inproc)
    port = _free_port()
    app_thread = _AppThread(make_app(ext), "127.0.0.1", port)
    app_thread.start()
    try:
        payload = json.dumps(body).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)

        def run_http():
            conn.request("POST", "/filter", body=payload,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()

        run_http()  # warm (and establish keep-alive)
        out["filter_http_p50_ms"] = p50_ms(run_http)
        conn.close()
    finally:
        app_thread.stop()
    out["http_overhead_ms"] = round(
        out["filter_http_p50_ms"] - out["filter_inproc_p50_ms"], 3)
    # ISSUE 20: the wire-codec point — TKW1 encode/decode p50 and the
    # frame-vs-compact-JSON size ratio on a fleet-shaped upsert wave
    # (the hot body shape: a dict list with identical keys, repeated
    # node/slice strings, a few badLinks rows). check.sh's perf smoke
    # ceilings the µs and floors the ratio via perf_floor.json "wire".
    from tpukube.sched import wirecodec

    wave = {"items": [
        {"name": name, "slice": cfg.slice_id,
         "topology": "16x16x16", "chips": mesh.chips_per_host,
         "device_ids": [f"{name}-chip-{i}"
                        for i in range(mesh.chips_per_host)],
         "badLinks": ([] if i % 7 else
                      [{"from": f"{name}-chip-0",
                        "to": f"{name}-chip-1"}]),
         "free": mesh.chips_per_host, "epoch": 3, "healthy": True}
        for i, name in enumerate(names)
    ]}
    json_len = len(wirecodec.dumps_json(wave))
    frame, _raw = wirecodec.encode_frame(wave, 1024)
    wirecodec.decode_frame(frame)  # warm

    out["wire_encode_us"] = round(1000 * p50_ms(
        lambda: wirecodec.encode_frame(wave, 1024)), 1)
    out["wire_decode_us"] = round(1000 * p50_ms(
        lambda: wirecodec.decode_frame(frame)), 1)
    out["wire_json_bytes"] = json_len
    out["wire_frame_bytes"] = len(frame)
    out["wire_ratio"] = round(json_len / len(frame), 2)
    return out


def kilonode() -> dict:
    """ISSUE 8 acceptance: the 1k-node / 100k-pod churn trace
    (scenario 10) on the discrete-event fake clock — pods-scheduled/sec
    and per-webhook p99 at kilonode scale, plus the wall the < 60s
    acceptance bounds. ``TPUKUBE_KILONODE_PODS`` scales it down for
    smoke runs (tools/check.sh uses 8000)."""
    from tpukube.sim import scenarios

    r = scenarios.run(10)
    return {
        "nodes": r["nodes"],
        "chips": r["chips"],
        "pods_total": r["pods_total"],
        "wall_s": r["wall_s"],
        "pods_per_sec": r["pods_per_sec"],
        "sim_seconds": r["sim_seconds"],
        "time_compression": r["time_compression"],
        "webhook_p99_ms": r["webhook_p99_ms"],
        "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
        "plan_hit_ratio": r["cycle"]["plan_hit_ratio"],
        "utilization_percent": r["utilization_percent"],
    }


def kilonode10k() -> dict:
    """ISSUE 10 acceptance: the 10k-node / 40k-chip churn drive
    (scenario 12) — throughput with the incremental snapshot + fast-
    state maintenance, plus the delta-apply vs forced-rebuild p50s.
    ``TPUKUBE_KILONODE10K_PODS`` scales it (default 40000; check.sh
    smoke uses a shorter fixed trace). Runs with the capacity flight
    recorder ON (ISSUE 17) so the ``capacity`` key reports the
    measured recorder overhead and the stranded-chip baseline the
    defrag work inherits."""
    import os

    from tpukube.sim import scenarios

    saved = os.environ.get("TPUKUBE_CAPACITY_ENABLED")
    os.environ["TPUKUBE_CAPACITY_ENABLED"] = saved or "1"
    try:
        r = scenarios.run(12)
    finally:
        if saved is None:
            del os.environ["TPUKUBE_CAPACITY_ENABLED"]
    cap = r.get("capacity") or {}
    stranded = r.get("stranded") or {}
    return {
        "nodes": r["nodes"],
        "chips": r["chips"],
        "pods_total": r["pods_total"],
        "wall_s": r["wall_s"],
        "pods_per_sec": r["pods_per_sec"],
        "time_compression": r["time_compression"],
        "webhook_p99_ms": r["webhook_p99_ms"],
        "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
        "plan_hit_ratio": r["cycle"]["plan_hit_ratio"],
        "fast_patches": r["cycle"]["fast_patches"],
        "fast_rebuilds": r["cycle"]["fast_rebuilds"],
        "gang_batches": r["cycle"]["gang_batches"],
        "snapshot": r["snapshot"],
        "utilization_percent": r["utilization_percent"],
        "capacity": {
            "overhead_pct": cap.get("overhead_pct"),
            "samples": cap.get("samples"),
            "stranded_chips": stranded.get("chips_requested", 0),
            "recoverable_chips": stranded.get("recoverable_chips", 0),
        },
    }


def recovery(nodes: tuple = ("1024", "10240")) -> dict:
    """ISSUE 11 acceptance: checkpoint-warm restart-to-serving vs the
    cold ``rebuild_extender`` on the SAME populated cluster, at 1k and
    10k nodes (the ≥10x acceptance point is 10240; check.sh's smoke
    gates the fast 1024 point). Warm = journal recovery (checkpoint
    head + lazy node restore + seeded snapshot + WAL tail replay +
    O(Δ) apiserver reconcile); cold = the legacy full rebuild
    (per-node decode + per-pod commit through recorded decisions).
    Both walls include the fresh Extender construction; best-of-3 per
    side so one page-cache hiccup cannot flip the recorded ratio."""
    import os
    import tempfile
    from dataclasses import replace as _dc_replace

    from tpukube.apiserver import rebuild_extender
    from tpukube.core.clock import FakeClock
    from tpukube.core.config import load_config
    from tpukube.core.types import PodGroup
    from tpukube.sched.extender import Extender
    from tpukube.sim.harness import SimCluster

    points = [
        p for p in (
            ("1024", "16,16,16", 256, 512),
            ("10240", "32,32,40", 256, 1024),
        ) if p[0] in nodes
    ]
    out: dict = {}
    for label, dims, gang_size, bursts in points:
        with tempfile.TemporaryDirectory(
            prefix="tpukube-bench-journal-"
        ) as td:
            cfg = load_config(env={
                "TPUKUBE_SIM_MESH_DIMS": dims,
                "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
                "TPUKUBE_BATCH_ENABLED": "1",
                "TPUKUBE_BATCH_MAX_PODS": "2048",
                "TPUKUBE_JOURNAL_ENABLED": "1",
                "TPUKUBE_JOURNAL_PATH": os.path.join(td, "wal.jsonl"),
            })
            clock = FakeClock()
            with SimCluster(cfg, clock=clock, in_process=True) as c:
                group = PodGroup("bench-train", min_member=gang_size)
                c.schedule_pending([
                    c.make_pod(f"bt-{i}", tpu=1, priority=100,
                               group=group)
                    for i in range(gang_size)
                ])
                c.schedule_pending([
                    c.make_pod(f"bb-{i}", tpu=1) for i in range(bursts)
                ])
                c.extender.journal.write_checkpoint_sync(
                    c.extender.checkpoint_doc()
                )
                cold_cfg = _dc_replace(cfg, journal_enabled=False,
                                       journal_path="")
                cold_walls, warm_walls = [], []
                warm_stats = None
                for _ in range(3):
                    t0 = time.perf_counter()
                    throwaway = Extender(cold_cfg, clock=clock)
                    rebuild_extender(throwaway, c._store_api)
                    cold_walls.append(time.perf_counter() - t0)
                for _ in range(3):
                    c.crash_extender()
                    t0 = time.perf_counter()
                    c.restart_extender()
                    warm_walls.append(time.perf_counter() - t0)
                    warm_stats = c.last_recovery
                    # let the post-recovery checkpoint land so every
                    # repeat measures the checkpoint-warm case the
                    # metric is named for
                    time.sleep(0.2)
                cold_s, warm_s = min(cold_walls), min(warm_walls)
                out[label] = {
                    "nodes": len(c.nodes),
                    "allocs": len(c.extender.state.allocations()),
                    "cold_rebuild_s": round(cold_s, 4),
                    "warm_recovery_s": round(warm_s, 4),
                    "replay_speedup": round(cold_s / warm_s, 1),
                    "warm_mode": warm_stats.get("mode"),
                    "warm_from_checkpoint": warm_stats.get("checkpoint"),
                    "recovery_core_s": warm_stats.get("recovery_s"),
                }
    return out


def kilonode_scaling() -> dict:
    """ISSUE 10 satellite: the node-count scaling sweep BENCH_r06
    needed — one churn point per fleet size (256 / 1k / 4k / 10k
    nodes), each emitting the normalized planning cost
    (``plan_ms_per_pod``) and the snapshot-maintenance cost per cycle
    (``snapshot_ms_per_cycle``), so the curve's bend is measured
    instead of inferred from a single operating point."""
    from tpukube.core.config import load_config as _load
    from tpukube.sim import scenarios

    points = [
        ("8,8,16", 256),     # 1024 chips
        ("16,16,16", 1024),  # 4096 chips
        ("32,32,16", 4096),  # 16384 chips
        ("32,32,40", 10240),  # 40960 chips
    ]
    out = {}
    for dims, nodes in points:
        chips = 1
        for d in dims.split(","):
            chips *= int(d)
        max_alive = min(4096, chips // 2)
        cfg = _load(env={
            "TPUKUBE_SIM_MESH_DIMS": dims,
            "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
            "TPUKUBE_BATCH_ENABLED": "1",
            "TPUKUBE_BATCH_MAX_PODS": "2048",
        })
        r = scenarios._kilonode_drive(
            cfg, metric=f"scaling_{nodes}",
            total_target=3 * max_alive,
            gang_size=min(256, chips // 8),
            max_alive=max_alive, delta_stats=True,
        )
        out[str(nodes)] = {
            "chips": chips,
            "pods_total": r["pods_total"],
            "wall_s": r["wall_s"],
            "pods_per_sec": r["pods_per_sec"],
            "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
            "snapshot_ms_per_cycle":
                r["snapshot"]["snapshot_ms_per_cycle"],
            "delta_apply_p50_ms": r["snapshot"]["delta_apply_p50_ms"],
            "rebuild_p50_ms": r["snapshot"]["rebuild_p50_ms"],
        }
    return out


def _shard_sweep_point(n: int, pods: int, transport: str,
                       wire_codec: str = "json") -> dict:
    """One replica-count point of the shard sweep: the scenario-12
    fleet (4 ICI slices of 16x16x40: 40,960 chips / 10,240 nodes) and
    churn trace, planned by N replicas over the given transport."""
    from tpukube.core.config import load_config as _load
    from tpukube.core.mesh import MeshSpec
    from tpukube.sim import scenarios

    cfg = _load(env={
        "TPUKUBE_SIM_MESH_DIMS": "16,16,40",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
        "TPUKUBE_BATCH_ENABLED": "1",
        "TPUKUBE_BATCH_MAX_PODS": "2048",
        "TPUKUBE_FILTER_FROM_PLAN": "1",
        "TPUKUBE_PLANNER_REPLICAS": str(n),
        "TPUKUBE_SHARD_TRANSPORT": transport,
        "TPUKUBE_WIRE_CODEC": wire_codec,
    })
    mesh = cfg.sim_mesh()
    slices = {
        f"s{i:02d}": MeshSpec(dims=mesh.dims,
                              host_block=mesh.host_block,
                              torus=mesh.torus)
        for i in range(4)
    }
    codec_tag = "" if wire_codec == "json" else f"_{wire_codec}"
    r = scenarios._kilonode_drive(
        cfg, metric=f"shard_{transport}_n{n}{codec_tag}",
        total_target=pods,
        gang_size=512, max_alive=8192, check_leaks=True,
        slices=slices, include_setup=False,
    )
    return {
        "nodes": r["nodes"],
        "chips": r["chips"],
        "pods_total": r["pods_total"],
        "wall_s": r["wall_s"],
        "setup_s": r.get("setup_s"),
        "pods_per_sec": r["pods_per_sec"],
        "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
        "webhook_p99_ms": r["webhook_p99_ms"],
        "utilization_percent": r["utilization_percent"],
        # bytes-per-churn-wave over the router->replica transport
        # (ISSUE 16): the wire baseline the ROADMAP codec item is
        # judged against — all zeros at the inprocess points
        "wire": r.get("wire"),
    }


def shard_scaling() -> dict:
    """ISSUE 13/14 acceptance: the replica-count scaling sweep on the
    scenario-12 fleet, in BOTH transports.

    ``inprocess`` (N = 1, 2, 4): PR 13's plane. The N=1 point is the
    plain UNSHARDED planner (the harness builds no router at
    planner_replicas=1 — the parity design), so N>1 deltas include the
    whole router tax; all points share ONE process and one GIL, so
    this half measures per-replica structure effects, not parallelism.

    ``process`` (N = 1, 2, 4, subprocess transport): each replica is
    its own planner DAEMON and the router fans calls out concurrently
    — the true multi-core pods/s curve (ISSUE 14 acceptance: the N=4
    aggregate must be >= 2x the N=1 process-mode point ON A MACHINE
    WITH THE CORES — ``cpus`` rides the result, and ``cpu_limited``
    marks points where os.cpu_count() < N+1, i.e. the workers are
    time-slicing cores and the sweep measures contention, not
    parallelism; a single-core CI box records the points but cannot
    demonstrate the scaling). The N=1 process point pays the full wire
    tax with zero parallelism, so ``speedup_vs_n1`` here is parallel
    scaling, not router-tax arithmetic; ``parallel_efficiency`` =
    speedup / N. Skipped (with a reason) where worker subprocesses
    cannot spawn.

    ``TPUKUBE_SHARD_SWEEP_PODS`` scales the trace (default 24000)."""
    import os

    pods = int(os.environ.get("TPUKUBE_SHARD_SWEEP_PODS", "24000"))
    cpus = os.cpu_count() or 1
    out: dict = {"inprocess": {}, "process": {"cpus": cpus}}
    for n in (1, 2, 4):
        out["inprocess"][str(n)] = _shard_sweep_point(n, pods,
                                                      "inprocess")
    base = out["inprocess"]["1"]["pods_per_sec"]
    for n in ("2", "4"):
        point = out["inprocess"][n]
        point["speedup_vs_n1"] = (
            round(point["pods_per_sec"] / base, 2) if base else None
        )
    try:
        for n in (1, 2, 4):
            out["process"][str(n)] = _shard_sweep_point(n, pods,
                                                        "subprocess")
    except Exception as e:
        # broad on purpose: wherever subprocess spawn is unavailable
        # (sandboxes, restricted CI) the sweep must SKIP with a
        # recorded reason, never fail the whole bench
        logging.getLogger("bench").warning(
            "process-mode shard sweep skipped: %s", e)
        out["process"] = {"skipped": str(e), "cpus": cpus}
        return out
    base = out["process"]["1"]["pods_per_sec"]
    for n in ("2", "4"):
        point = out["process"][n]
        speedup = (round(point["pods_per_sec"] / base, 2)
                   if base else None)
        point["speedup_vs_n1"] = speedup
        point["parallel_efficiency"] = (
            round(speedup / int(n), 3) if speedup else None
        )
        # N workers + the router need N+1 schedulable cores before the
        # efficiency number means parallelism rather than time-slicing
        point["cpu_limited"] = cpus < int(n) + 1
    # ISSUE 20: the wire before/after in ONE run — the same N=2
    # process point re-driven with the TKW1 binary codec, so the
    # recorded bytes/wave ratio is json-vs-binary on an identical
    # fixed trace (the acceptance asks >= 3x; check.sh's codec smoke
    # floors it via perf_floor.json "wire")
    try:
        binary_pt = _shard_sweep_point(2, pods, "subprocess",
                                       wire_codec="binary")
    except Exception as e:
        logging.getLogger("bench").warning(
            "codec-on shard point skipped: %s", e)
        out["wire_codec"] = {"skipped": str(e)}
        return out
    wj = out["process"]["2"].get("wire") or {}
    wb = binary_pt.get("wire") or {}
    jpw, bpw = wj.get("bytes_per_wave"), wb.get("bytes_per_wave")
    out["wire_codec"] = {
        "json": wj,
        "binary": wb,
        "bytes_per_wave_ratio": (round(jpw / bpw, 2)
                                 if jpw and bpw else None),
        "pods_per_sec_binary": binary_pt["pods_per_sec"],
    }
    return out


def kilonode100k() -> dict:
    """ISSUE 13 acceptance: scenario 14 — the 100k-node sharded drive
    (10 slices x 32x32x40 behind 4 planner replicas). ``setup_s`` is
    the one-time fleet ingest, excluded from the throughput wall.
    ``TPUKUBE_KILONODE100K_PODS``/``TPUKUBE_SHARD_SLICES`` scale it."""
    from tpukube.sim import scenarios

    r = scenarios.run(14)
    return {
        "nodes": r["nodes"],
        "chips": r["chips"],
        "pods_total": r["pods_total"],
        "wall_s": r["wall_s"],
        "setup_s": r.get("setup_s"),
        "pods_per_sec": r["pods_per_sec"],
        "time_compression": r["time_compression"],
        "webhook_p99_ms": r["webhook_p99_ms"],
        "plan_ms_per_pod": r["cycle"]["plan_ms_per_pod"],
        "plan_hit_ratio": r["cycle"]["plan_hit_ratio"],
        "replicas": r["shard"]["replicas"],
        "rendezvous": r["shard"]["rendezvous"],
        "utilization_percent": r["utilization_percent"],
    }


def _coldstart_fleet(n_nodes: int, hetero: bool) -> tuple[list, list]:
    """Mint ``n_nodes`` worth of node-annotation items (the
    ``upsert_nodes`` wire shape) over 10,240-node slices of 32x32x40
    (scenario 14's geometry; a smaller point uses one right-sized
    slice). ``hetero`` sprinkles per-node health flips and bad ICI
    links so payload shapes vary across the fleet the way a real aging
    fleet's do — the homogeneous run is the mesh-fragment memo's best
    case, the heterogeneous run its honest case. Returns ``(items,
    keepalive)`` — the caller must hold ``keepalive`` (the minted
    NodeInfo fleet) across the measurement: scenario 14's setup runs
    with the sim's whole fleet live on the heap, and the per-node
    path's allocation storms pay GC full-heap scans against it (the
    dominant fleet-scale term the bulk path avoids)."""
    from tpukube.core import codec
    from tpukube.core.mesh import MeshSpec
    from tpukube.core.types import ChipInfo, Health, NodeInfo

    slice_nodes = 10240
    if n_nodes <= slice_nodes:
        # one right-sized slice: 4 chips/node under host_block (2,2,1)
        chips = n_nodes * 4
        z = max(2, chips // (32 * 32))
        dims = (32, 32, z) if chips >= 32 * 32 * 2 else (16, 16, 16)
        meshes = {"s00": MeshSpec(dims=dims, host_block=(2, 2, 1))}
    else:
        meshes = {
            f"s{i:02d}": MeshSpec(dims=(32, 32, 40),
                                  host_block=(2, 2, 1))
            for i in range((n_nodes + slice_nodes - 1) // slice_nodes)
        }
    items: list[dict] = []
    keepalive: list = []
    for sid in sorted(meshes):
        m = meshes[sid]
        for host in m.all_hosts():
            if len(items) >= n_nodes:
                break
            name = f"{sid}-{host}"
            coords = m.coords_of_host(host)
            chips = [
                ChipInfo(chip_id=f"{name}-chip-{i}", index=i, coord=c,
                         hbm_bytes=16 * 2 ** 30)
                for i, c in enumerate(coords)
            ]
            if hetero and len(items) % 7 == 0:
                chips[0].health = Health.UNHEALTHY
            info = NodeInfo(name=name, chips=chips, slice_id=sid)
            if hetero and len(items) % 13 == 0:
                # one bad link between two ICI-adjacent chips of this
                # node's own 2x2 host block
                for other in coords[1:]:
                    if other in m.neighbors(coords[0]):
                        info.bad_links = [(coords[0], other)]
                        break
            keepalive.append(info)
            items.append({
                "name": name,
                "annotations": codec.annotate_node(info, m),
            })
    return items, keepalive


def _coldstart_point(n_nodes: int, hetero: bool) -> dict:
    """One coldstart measurement: the bulk ``upsert_nodes`` ingest wall
    vs the legacy per-node ``upsert_node`` decision loop, on fresh
    extenders over the same minted fleet (annotation encode is setup,
    untimed; the minted fleet stays LIVE on the heap for both arms —
    see _coldstart_fleet). ``bulk_warm_s`` adds the deferred decode
    the background warmer drains off the serving path — reported so
    the lazy contract's deferred cost stays visible next to the
    headline."""
    import gc

    from tpukube.core.config import load_config
    from tpukube.sched.extender import Extender

    items, keepalive = _coldstart_fleet(n_nodes, hetero)
    out: dict = {"nodes": len(items), "chips": len(items) * 4,
                 "hetero": hetero}

    cfg = load_config(env={})
    ext = Extender(cfg)
    gc.collect()
    t0 = time.perf_counter()
    results = ext.upsert_nodes_many(items)
    out["bulk_s"] = round(time.perf_counter() - t0, 3)
    bad = [r for r in results if r != {"ours": True}]
    if bad:
        raise RuntimeError(f"coldstart bulk ingest rejected items: "
                           f"{bad[:3]}")
    t0 = time.perf_counter()
    while ext.state.warm_pending(2048):
        pass
    out["bulk_warm_s"] = round(time.perf_counter() - t0, 3)
    stats = ext.state.ingest_stats()
    out["decode_cache_hit_rate"] = stats["decode_cache_hit_rate"]
    # drop the bulk arm's ledger before timing the per-node arm (its
    # GC cost must scan the shared minted fleet, not the rival arm's)
    ext.state.retire()
    del ext
    gc.collect()

    ext2 = Extender(cfg)
    ext2.bulk_ingest = False
    t0 = time.perf_counter()
    results = ext2.upsert_nodes_many(items)
    out["per_node_s"] = round(time.perf_counter() - t0, 3)
    bad = [r for r in results if r != {"ours": True}]
    if bad:
        raise RuntimeError(f"coldstart per-node ingest rejected "
                           f"items: {bad[:3]}")
    out["speedup"] = (round(out["per_node_s"] / out["bulk_s"], 1)
                      if out["bulk_s"] > 0 else None)
    del ext2, keepalive
    gc.collect()
    return out


def coldstart() -> dict:
    """ISSUE 15 acceptance: cold-start fleet ingestion — the bulk
    ``upsert_nodes`` fast path (probe-validated lazy ingest, one
    epoch/delta/journal seam per batch) vs the per-node ``upsert_node``
    decision loop, at 1k / 10k / ~102k nodes, homogeneous and (at the
    10k point) heterogeneous payloads. The ~102k point is scenario
    14's fleet shape (10 slices x 32x32x40 = 102,400 nodes / 409,600
    chips) — the acceptance point, where the per-node path's GC
    full-heap scans over the live fleet make the gap superlinear
    (~5x+ here vs ~3x at 10k); check.sh floors the 10k point (fast
    enough for CI) and BENCH records this full sweep."""
    return {
        "1k": _coldstart_point(1024, hetero=False),
        "10k": _coldstart_point(10240, hetero=False),
        "10k_hetero": _coldstart_point(10240, hetero=True),
        "100k": _coldstart_point(102400, hetero=False),
    }


def run() -> dict:
    from tpukube.sim import scenarios

    t0 = time.perf_counter()
    result = scenarios.multi_tenant_northstar(None)
    result["sched_wall_s"] = round(time.perf_counter() - t0, 2)
    c = scenarios.churn(None)
    result["churn"] = {
        k: c[k] for k in (
            "util_min_after_refill_percent", "resched_p50_s",
            "resched_p99_s", "waves", "wave_size", "lifecycle_releases",
            # per-phase timeline stats for the churn run too: re-schedule
            # spread attributed to a phase, not just observed
            "phases",
        ) if k in c
    }
    result["process"] = process_stats()
    result["lint"] = lint_stats()
    result["chaos"] = chaos_stats()
    result["sched_micro"] = sched_micro()
    result["kilonode"] = kilonode()
    result["kilonode10k"] = kilonode10k()
    result["kilonode_scaling"] = kilonode_scaling()
    result["shard_scaling"] = shard_scaling()
    result["kilonode100k"] = kilonode100k()
    result["recovery"] = recovery()
    result["coldstart"] = coldstart()
    result["elasticity"] = elasticity()
    return result


def chaos_stats() -> dict:
    """Chaos-scenario cost tracking (ISSUE 4): wall time of the seeded
    apiserver-chaos run (scenario 8) and of the crash-recovery run
    (scenario 9), plus the recovery latency proper (extender crash ->
    ledger converged). Tracked per PR like the scheduler numbers so a
    regression in retry/rebuild cost shows up in BENCH_*.json."""
    from tpukube.sim import scenarios

    s8 = scenarios.run(8)
    s9 = scenarios.run(9)
    return {
        "scenario8_wall_s": s8["wall_s"],
        "scenario8_faults_injected": s8["faults"]["injected"],
        "scenario9_wall_s": s9["wall_s"],
        "recovery_s": s9["recovery_s"],
    }


def elasticity() -> dict:
    """Fleet elasticity tracking (ISSUE 19), three points. (1) the
    seeded maintenance-storm scenario (15) — drain/spot-churn/
    autoscaler chaos — wall time plus the disruption-vs-budget and
    audit numbers its invariants gate on. (2) disruption-per-drain and
    drained-chips/s: one graceful drain of a resident-loaded 64-chip
    slice under eviction budget 2, wall from ``begin()`` to the slice
    leaving the ledger (cordon -> budgeted migrate -> un-ingest, the
    whole choreography). (3) time-to-capacity at the 10k-node point:
    bulk provisioning of a fresh 10,240-node slice (the autoscaler's
    scale-up wire path, ``upsert_nodes_many``) until the new capacity
    is visible to the placement sweeps — the region-scale answer to
    'how long after a scale-up decision can pods actually land'."""
    from tpukube.core.clock import FakeClock
    from tpukube.core.config import load_config
    from tpukube.core.mesh import MeshSpec
    from tpukube.sched.extender import Extender
    from tpukube.sim import scenarios
    from tpukube.sim.harness import SimCluster

    out: dict = {}
    t0 = time.perf_counter()
    s15 = scenarios.run(15)
    out["scenario15_wall_s"] = round(time.perf_counter() - t0, 2)
    out["drains_survived"] = s15["value"]
    out["peak_tick_moves"] = s15["peak_tick_moves"]
    out["budget_moves"] = s15["budget_moves"]
    out["audit_divergences"] = s15["snapshot_audit"]["divergences"]

    cfg = load_config(env={
        "TPUKUBE_DRAIN_ENABLED": "1",
        "TPUKUBE_DRAIN_MAX_CONCURRENT_MOVES": "2",
    })
    mesh = MeshSpec(dims=(4, 4, 4), host_block=(2, 2, 1))
    with SimCluster(cfg, clock=FakeClock(),
                    slices={"s0": mesh, "s1": mesh}) as c:
        ext = c.extender
        for i in range(16):
            c.schedule(c.make_pod(f"d{i}", tpu=2))
        doomed = sorted(n for n in ext.state.node_names()
                        if ext.state.slice_of_node(n) == "s0")
        t0 = time.perf_counter()
        ext.drain.begin(doomed, reason="bench")
        ticks = 0
        while ext.drain.active():
            ext.drain.tick()
            c.clock.advance(1.0)
            ticks += 1
            if ticks > 200:
                raise RuntimeError("bench drain failed to converge")
        wall = time.perf_counter() - t0
        st = ext.drain.statusz()
        if st["completed"] != 1 or "s0" in ext.state.slice_ids():
            raise RuntimeError(f"bench drain did not complete: {st}")
        out["drain_wall_s"] = round(wall, 3)
        out["drain_evictions"] = st["evictions_total"]
        out["drain_peak_tick_moves"] = st["peak_tick_moves"]
        out["drained_chips_per_s"] = round(
            st["chips_removed_total"] / max(wall, 1e-6), 1)

    items, keepalive = _coldstart_fleet(10240, hetero=False)
    ext = Extender(load_config(env={}))
    t0 = time.perf_counter()
    results = ext.upsert_nodes_many(items)
    snap = ext.snapshots.current()
    free = sum(snap.slice(sid).free_chips
               for sid in ext.state.slice_ids())
    out["scale_up_10k_to_capacity_s"] = round(
        time.perf_counter() - t0, 3)
    bad = [r for r in results if r != {"ours": True}]
    if bad or free < len(items) * 4:
        raise RuntimeError(
            f"scale-up point broken: {len(bad)} rejects, "
            f"{free} chips visible")
    ext.state.retire()
    del ext, keepalive
    return out


if __name__ == "__main__":
    print(json.dumps(run()))

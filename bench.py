"""North-star benchmark: BASELINE config 5 on the sim control plane.

Delegates to tpukube.sim.scenarios.multi_tenant_northstar — the SAME code
path the acceptance test (tests/test_config5.py shape) and `tpukube-sim 5`
run — and prints one JSON line with the headline metric. vs_baseline is
measured utilization over the BASELINE.json target (>= 95%).
"""

from __future__ import annotations

import json
import time


def run() -> dict:
    from tpukube.sim import scenarios

    t0 = time.perf_counter()
    result = scenarios.multi_tenant_northstar(None)
    result["sched_wall_s"] = round(time.perf_counter() - t0, 2)
    return result


if __name__ == "__main__":
    print(json.dumps(run()))

"""North-star benchmark: BASELINE config 5 on the sim control plane.

Runs the multi-tenant scenario (128-chip 8x8x2 mesh / 32 hosts: 80 burst
inference pods, then a 64-pod priority-100 training gang that must preempt
and land ICI-contiguously, then burst backfill) through the REAL extender
HTTP stack, and prints one JSON line with the headline metric.

vs_baseline is measured utilization over the BASELINE.json target (>= 95%).
"""

from __future__ import annotations

import json
import time
import urllib.request


def run() -> dict:
    from tpukube.core.config import load_config
    from tpukube.core.types import PodGroup
    from tpukube.sim import SimCluster

    cfg = load_config(env={
        "TPUKUBE_SIM_MESH_DIMS": "8,8,2",
        "TPUKUBE_SIM_HOST_BLOCK": "2,2,1",
    })
    t0 = time.perf_counter()
    with SimCluster(cfg) as c:
        for i in range(80):
            c.schedule(c.make_pod(f"infer-{i}", tpu=1, priority=0))
        group = PodGroup("llama-70b", min_member=64)
        for i in range(64):
            c.schedule(c.make_pod(f"train-{i}", tpu=1, priority=100,
                                  group=group))
        # backfill evicted burst load until the cluster refuses
        fill = 0
        while True:
            try:
                c.schedule(c.make_pod(f"fill-{fill}", tpu=1, priority=0))
                fill += 1
            except RuntimeError:
                break
        wall = time.perf_counter() - t0

        with urllib.request.urlopen(f"{c.base_url}/metrics", timeout=5) as r:
            text = r.read().decode()
        series = {
            line.split(" ")[0]: float(line.split(" ")[1])
            for line in text.splitlines()
            if line and not line.startswith("#")
        }
        util = series["tpu_chip_utilization_percent"]
        return {
            "metric": "cluster_tpu_utilization_percent",
            "value": round(util, 2),
            "unit": "%",
            "vs_baseline": round(util / 95.0, 4),
            "gang_p50_s": round(
                series['gang_schedule_latency_seconds{quantile="0.5"}'], 4
            ),
            "preemptions": int(series["tpukube_preemptions_total"]),
            "sched_wall_s": round(wall, 2),
            "pods_placed": int(series["tpukube_binds_total"]),
        }


if __name__ == "__main__":
    print(json.dumps(run()))

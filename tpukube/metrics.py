"""Metrics export (SURVEY.md §6 "Metrics / logging / observability").

The reference lineage only has glog; BASELINE's north-star metrics demand
more: cluster TPU-chip utilization % and the gang-schedule latency
distribution. The renderers here are thin builders over the
``tpukube.obs.registry`` metrics registry (Counter/Gauge/Summary/
Histogram with label sets) — no prometheus_client dependency — and a
tiny threaded HTTP server for the node agent (the extender serves
/metrics from its aiohttp app). Every legacy series name/label renders
byte-identically to the pre-registry renderers (golden-file test in
tests/test_obs.py); the registry additionally contributes histogram
``_bucket`` series for the gang-commit and webhook latency
distributions.

Exported series (extender):
  tpu_chip_utilization_percent            — north star #1
  gang_schedule_latency_seconds{quantile} — north star #2 (+ _count/_sum)
  gang_schedule_latency_seconds_bucket{le}          — histogram buckets
  tpukube_binds_total, tpukube_gang_rollbacks_total,
  tpukube_preemptions_total, tpukube_webhook_latency_seconds{handler,quantile}
  tpukube_webhook_latency_seconds_bucket{handler,le}

Exported series (node agent):
  tpukube_plugin_allocations_total, tpukube_plugin_devices{health}
  tpukube_chip_healthy{chip}, tpukube_chip_duty_cycle_percent{chip},
  tpukube_chip_hbm_used_bytes{chip}, tpukube_chip_hbm_total_bytes{chip},
  tpukube_chip_ici_link_errors_total{chip},
  tpukube_chip_health_transitions_total{chip}, tpukube_node_chips{state}
  (telemetry sampler — obs/health.py)

Both daemons additionally export tpukube_events_total{reason} when an
event journal (obs/events.py) is attached.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

from tpukube.obs.registry import (
    Registry,
    escape_label_value as _esc,  # noqa: F401  (legacy import surface)
    format_sample as _fmt,
    quantile,
)

__all__ = [
    "quantile", "MetricsServer", "build_extender_registry",
    "build_plugin_registry", "build_router_registry",
    "build_syncer_registry",
    "render_extender_metrics", "render_federated_metrics",
    "render_plugin_metrics",
    "render_router_metrics", "render_syncer_metrics",
]


def build_extender_registry(extender, reconcile=None, evictions=None,
                            node_refresh=None, lifecycle=None) -> Registry:
    """Registry for an Extender (tpukube.sched.extender); pass the
    daemon's AllocReconcileLoop / EvictionExecutor /
    NodeTopologyRefreshLoop / PodLifecycleReleaseLoop to export their
    counters (the divergence/reconcile/eviction/release story operators
    alarm on — a flat releases counter under churn means the release
    watch is dead and chips are leaking)."""
    reg = Registry()
    # everything is pull-based (fn/values_fn against the live daemon
    # objects): a registry built once and rendered per scrape — the
    # natural long-lived usage — must never serve construction-time
    # snapshots of the north-star series
    reg.gauge("tpu_chip_utilization_percent",
              fn=lambda: 100.0 * extender.state.utilization())

    reg.summary("gang_schedule_latency_seconds",
                quantiles=(0.5, 0.9, 0.99),
                values_fn=lambda: list(extender.gang.commit_latencies))
    # the distribution the summary's fixed quantiles flatten: the gang
    # manager's persistent histogram — monotonic cumulative bucket
    # counters (observed at commit time, never a window snapshot), so
    # Prometheus can rate()/aggregate them across scrapes and instances
    reg.register(extender.gang.commit_hist)

    reg.gauge("tpukube_ici_links_down", fn=lambda: sum(
        len(extender.state.broken_links(sid))
        for sid in extender.state.slice_ids()
    ))

    reg.counter("tpukube_binds_total",
                fn=lambda: extender.binds_total)
    reg.counter("tpukube_gang_rollbacks_total",
                fn=lambda: extender.gang.rollbacks)
    reg.counter("tpukube_preemptions_total",
                fn=lambda: extender.preemptions)

    web = reg.summary("tpukube_webhook_latency_seconds",
                      quantiles=(0.5, 0.99), emit_count_sum=False)
    for handler in extender.latencies:
        web.labels(_values_fn=(lambda h=handler: list(extender.latencies[h])),
                   handler=handler)
    # per-handler monotonic buckets, observed where the daemon records
    # each sample (the extender's persistent histogram)
    reg.register(extender.webhook_hist)

    # evicted-but-unconfirmed preemption victims: non-zero means gang
    # binds are gated on graceful terminations in progress
    reg.gauge("tpukube_gang_victims_terminating",
              fn=lambda: extender.gang.terminating_count())

    pending = reg.gauge("tpukube_evictions_pending")
    if evictions is not None:
        pending.set_function(lambda: evictions.depth())
        reg.counter("tpukube_evictions_total",
                    fn=lambda: evictions.evicted)
        reg.counter("tpukube_evictions_blocked_total",
                    fn=lambda: evictions.blocked)
        reg.counter("tpukube_eviction_failures_total",
                    fn=lambda: evictions.failures)
        # a PDB-wedged eviction is a capacity leak in progress: alarm on
        # age, not just depth
        reg.gauge("tpukube_eviction_oldest_age_seconds",
                  fn=lambda: evictions.oldest_age_seconds())
    else:
        # no executor (sim/dev): the queue depth is still the operator's
        # double-allocation early-warning
        pending.set_function(lambda: len(extender.pending_evictions))
    if reconcile is not None:
        reg.counter("tpukube_reconciles_total",
                    fn=lambda: reconcile.reconciled)
    if node_refresh is not None:
        reg.counter("tpukube_node_refreshes_total",
                    fn=lambda: node_refresh.refreshed)
    if lifecycle is not None:
        reg.counter("tpukube_lifecycle_releases_total",
                    fn=lambda: lifecycle.released)
    events = getattr(extender, "events", None)
    if events is not None:
        _add_events_counter(reg, events)
    # epoch-cached scheduling snapshot (sched/snapshot.py): cache
    # effectiveness counters + the per-slice fragmentation numbers the
    # cache makes cheap enough to serve on every scrape
    snapshots = getattr(extender, "snapshots", None)
    if snapshots is not None:
        _add_snapshot_metrics(reg, snapshots)
    # durable-state journal (sched/journal.py): series render only
    # when journal_enabled built a StateJournal — the legacy
    # exposition stays byte-identical with the journal off
    journal = getattr(extender, "journal", None)
    if journal is not None:
        _add_journal_metrics(reg, journal)
    # bulk cold-start ingestion + generation-based incremental resync
    # (ISSUE 15): series render only while the features are on
    if getattr(extender, "bulk_ingest", False):
        st = extender.state
        reg.counter(
            "tpukube_ingest_nodes_total",
            fn=lambda: st.ingest_nodes_total,
            help_text="Nodes ingested through the bulk cold-start "
                      "fast path (handle('upsert_nodes')).")
        reg.summary(
            "tpukube_ingest_seconds",
            quantiles=(0.5, 0.99),
            values_fn=st.ingest_seconds_snapshot,
            help_text="Wall time per bulk-ingest batch (probe + "
                      "seeding; the deferred decode drains on the "
                      "background warmer).")
    if (lifecycle is not None
            and getattr(extender, "resync_incremental", False)
            and hasattr(lifecycle, "resync_full")):
        reg.counter(
            "tpukube_resync_full_total",
            fn=lambda: lifecycle.resync_full,
            help_text="Lifecycle resyncs that read the FULL ledger "
                      "(the one bootstrap read, plus any generation-"
                      "log gap/restart fallback).")
        reg.counter(
            "tpukube_resync_incremental_total",
            fn=lambda: lifecycle.resync_incremental,
            help_text="Lifecycle resyncs served O(Δ) from the "
                      "generation log (allocs_since adds/removes).")
        reg.counter(
            "tpukube_resync_bytes_total",
            fn=lambda: lifecycle.resync_bytes,
            help_text="Wire-shape bytes the resync reads moved "
                      "(encoded alloc lengths) — O(changed-allocs) "
                      "per churn wave when the incremental path "
                      "holds.")
    # batched scheduling cycles (sched/cycle.py): series render only
    # when batch_enabled actually built a planner — the legacy
    # exposition stays byte-identical with batching off
    cycle = getattr(extender, "cycle", None)
    if cycle is not None:
        _add_cycle_metrics(reg, cycle)
    # multi-tenant serving plane (tpukube/tenancy): series render only
    # when tenancy_enabled built a TenantPlane — tenancy-off exposition
    # stays byte-identical
    tenants = getattr(extender, "tenants", None)
    if tenants is not None:
        _add_tenant_metrics(reg, tenants)
    # decision provenance + cycle phase profiling (obs/decisions.py):
    # series render only when decisions_enabled built a DecisionLog —
    # provenance-off exposition stays byte-identical
    decisions = getattr(extender, "decisions", None)
    if decisions is not None:
        _add_decision_metrics(reg, extender, decisions)
    # capacity analytics & demand forensics (obs/capacity.py): series
    # render only when capacity_enabled built a CapacityRecorder —
    # capacity-off exposition stays byte-identical
    capacity = getattr(extender, "capacity", None)
    if capacity is not None:
        _add_capacity_metrics(reg, capacity)
    # fleet elasticity (sched/drain.py + sched/autoscale.py, ISSUE 19):
    # series render only when the flags built the objects —
    # elasticity-off exposition stays byte-identical
    drain = getattr(extender, "drain", None)
    if drain is not None:
        _add_drain_metrics(reg, drain)
    autoscaler = getattr(extender, "autoscaler", None)
    if autoscaler is not None:
        _add_autoscaler_metrics(reg, autoscaler)
    # unified retry/circuit layer (ISSUE 4): series render only when
    # the daemon actually wired the channel objects — sim/dev
    # extenders keep the legacy exposition byte-identical
    _add_retry_metrics(
        reg,
        retriers=[r for r in (getattr(extender, "api_retrier", None),)
                  if r is not None],
        circuits=[c for c in (getattr(extender, "api_circuit", None),)
                  if c is not None],
    )
    if getattr(extender, "degraded_gate", None) is not None:
        reg.gauge(
            "tpukube_degraded_mode",
            fn=lambda: 1.0 if extender._degraded_reason() else 0.0,
            help_text="1 while the extender fails filter/bind safe "
                      "because its apiserver circuit is open.")
    return reg


def render_extender_metrics(extender, reconcile=None, evictions=None,
                            node_refresh=None, lifecycle=None) -> str:
    """Prometheus text for an Extender — see build_extender_registry."""
    return build_extender_registry(
        extender, reconcile=reconcile, evictions=evictions,
        node_refresh=node_refresh, lifecycle=lifecycle,
    ).render()


def build_router_registry(router) -> Registry:
    """Registry for a :class:`~tpukube.sched.shard.ShardRouter`
    (planner_replicas > 1, ISSUE 13): router topology, routed volume,
    the two-phase rendezvous ledger, and one summary row per replica —
    the per-replica observability leg of the sharded plane. In a real
    deployment each replica additionally serves its own full
    ``render_extender_metrics`` exposition from its own listener; the
    router-level series are the cross-shard rollup."""
    reg = Registry()
    reg.gauge("tpukube_router_replicas",
              fn=lambda: len(router.replicas))
    rdv = reg.counter("tpukube_router_rendezvous_total")
    rdv.labels(outcome="prepared").set_function(
        lambda: router.rendezvous_prepared)
    rdv.labels(outcome="committed").set_function(
        lambda: router.rendezvous_committed)
    rdv.labels(outcome="aborted").set_function(
        lambda: router.rendezvous_aborted)
    up = reg.gauge("tpukube_replica_up")
    nodes = reg.gauge("tpukube_replica_nodes")
    slices = reg.gauge("tpukube_replica_slices")
    allocs = reg.gauge("tpukube_replica_allocs")
    routed = reg.counter("tpukube_replica_pods_routed_total")
    binds = reg.counter("tpukube_replica_binds_total")
    util = reg.gauge("tpukube_replica_utilization")
    depth = reg.gauge("tpukube_replica_queue_depth")

    # one summary read per replica feeds the whole row, memoized for
    # this REGISTRY's lifetime: render_router_metrics builds a fresh
    # registry per scrape, so each scrape reads each replica once —
    # not once per gauge (6 HTTP round-trips per replica per scrape in
    # process mode). A dead/unreachable replica renders zeros (its
    # liveness gauge carries the signal).
    summary_memo: dict[int, dict] = {}

    def _summary(rep) -> dict:
        from tpukube.sched.shard import ReplicaUnavailable

        cached = summary_memo.get(rep.index)
        if cached is not None:
            return cached
        if rep.killed:
            doc = {}
        else:
            try:
                doc = rep.transport.summary()
            except ReplicaUnavailable:
                doc = {}
        summary_memo[rep.index] = doc
        return doc

    for rep in router.replicas:
        name = rep.name
        up.labels(replica=name).set_function(
            lambda r=rep: 1.0 if r.alive else 0.0)
        nodes.labels(replica=name).set_function(
            lambda r=rep: _summary(r).get("nodes", 0))
        slices.labels(replica=name).set_function(
            lambda r=rep: len(_summary(r).get("slices", ())))
        allocs.labels(replica=name).set_function(
            lambda r=rep: _summary(r).get("allocs", 0))
        routed.labels(replica=name).set_function(
            lambda r=rep: r.pods_routed)
        binds.labels(replica=name).set_function(
            lambda r=rep: _summary(r).get("binds_total", 0))
        util.labels(replica=name).set_function(
            lambda r=rep: _summary(r).get("utilization", 0.0))
        depth.labels(replica=name).set_function(
            lambda r=rep: _summary(r).get("queue_depth", 0))
    if getattr(router, "mode", "inprocess") == "subprocess":
        # transport telemetry (ISSUE 14): rendered ONLY in process
        # mode — the in-process router has no wire to measure, and its
        # exposition stays byte-identical to PR 13's
        rtt = reg.summary(
            "tpukube_replica_rtt_seconds",
            help_text="Router->replica request round-trip time over "
                      "the subprocess transport, per replica.")
        checks = reg.counter(
            "tpukube_replica_health_checks_total",
            help_text="Replica health checks run by the router "
                      "(subprocess transport).")
        fails = reg.counter(
            "tpukube_replica_health_check_failures_total",
            help_text="Health checks that failed and marked the "
                      "replica dead (crash_replica semantics).")
        for rep in router.replicas:
            name = rep.name
            rtt.labels(lambda r=rep: r.transport.rtt_snapshot(),
                       replica=name)
            checks.labels(replica=name).set_function(
                lambda r=rep: r.transport.health_checks)
            fails.labels(replica=name).set_function(
                lambda r=rep: r.transport.health_failures)
        wire = reg.counter(
            "tpukube_router_wire_bytes_total",
            help_text="Bytes over the router->replica subprocess "
                      "transport, per op and direction (dir=tx is the "
                      "request payload, dir=rx the response body) — "
                      "the wire-cost baseline the ROADMAP codec item "
                      "is judged against.")
        snaps = []
        for rep in router.replicas:
            # snapshot at registry build: the registry is rebuilt per
            # scrape, so the values are scrape-current without taking
            # the transport lock once per rendered sample
            snap = rep.transport.wire_snapshot() \
                if hasattr(rep.transport, "wire_snapshot") else None
            if not snap:
                continue
            snaps.append((rep.name, snap))
            for op, cell in sorted(snap["by_op"].items()):
                for d in ("tx", "rx"):
                    wire.labels(op=op, dir=d, replica=rep.name) \
                        .set_function(lambda v=cell[d]: v)
        if any("codec" in snap for _, snap in snaps):
            # wire codec savings (ISSUE 20): rendered ONLY when a
            # binary-codec transport exists, so the default (json)
            # plane's exposition stays byte-identical
            saved = reg.counter(
                "tpukube_router_wire_saved_bytes_total",
                help_text="Bytes the binary wire codec kept off the "
                          "router->replica transport, per op and "
                          "replica (pre-compression frame bytes minus "
                          "bytes actually sent).")
            for name, snap in snaps:
                for op, cell in sorted(snap["by_op"].items()):
                    if "codec" not in cell:
                        continue
                    delta = max(
                        0, (cell.get("raw_tx", 0)
                            + cell.get("raw_rx", 0))
                        - (cell["tx"] + cell["rx"]))
                    saved.labels(op=op, replica=name) \
                        .set_function(lambda v=delta: v)
    return reg


def render_router_metrics(router) -> str:
    """Prometheus text for a ShardRouter — see build_router_registry."""
    return build_router_registry(router).render()


def _with_replica_label(line: str, replica: str) -> str:
    """One exposition sample line with ``replica="<name>"`` appended to
    its label set (added when absent — a worker never labels itself)."""
    if "{" in line:
        close = line.rindex("}")
        inner = line[line.index("{") + 1:close]
        if 'replica="' in inner:
            return line
        head = line[:line.index("{")]
        return (f'{head}{{{inner},replica="{replica}"}}'
                + line[close + 1:])
    name, _, value = line.partition(" ")
    return f'{name}{{replica="{replica}"}} {value}'


def _merge_exposition(acc: dict, text: str,
                      replica: Optional[str]) -> None:
    """Fold one exposition into the family accumulator: HELP/TYPE are
    kept from the FIRST source that declared them (the replicas run
    identical code, so later declarations are identical), samples
    append under their family so the merged render keeps each family
    contiguous with exactly one TYPE line — the promlint contract."""
    fam: Optional[str] = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            name = line.split(None, 3)[2]
            cell = acc.setdefault(
                name, {"help": None, "type": None, "samples": []})
            kind = "help" if line.startswith("# HELP ") else "type"
            if cell[kind] is None:
                cell[kind] = line
            fam = name
            continue
        if line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(None, 1)[0]
        family = fam if fam is not None and name.startswith(fam) \
            else name
        cell = acc.setdefault(
            family, {"help": None, "type": None, "samples": []})
        if replica is not None:
            line = _with_replica_label(line, replica)
        cell["samples"].append(line)


def render_federated_metrics(router) -> str:
    """The router's aggregated /metrics (ISSUE 16): the router's own
    registry plus every alive replica's FULL worker exposition merged
    in with a ``replica`` label — one scrape target for the whole
    sharded control plane, replacing N per-replica scrape configs.
    With ``planner_replicas: 1`` in-process this returns the sole
    planner's exposition verbatim (off-is-off: byte-identical to the
    unsharded scrape)."""
    sole = getattr(router, "_sole", None)
    if sole is not None:
        return render_extender_metrics(sole)
    from tpukube.sched.shard import ReplicaUnavailable, ShardError

    acc: dict = {}
    _merge_exposition(acc, render_router_metrics(router), None)
    for rep in router.replicas:
        if not rep.alive:
            continue
        try:
            text = rep.transport.metrics_text()
        except (ReplicaUnavailable, ShardError):
            continue  # liveness is tpukube_replica_up's job
        _merge_exposition(acc, text, rep.name)
    parts: list[str] = []
    for cell in acc.values():
        if cell["help"] is not None:
            parts.append(cell["help"])
        if cell["type"] is not None:
            parts.append(cell["type"])
        parts.extend(cell["samples"])
    return "\n".join(parts) + "\n"


def build_plugin_registry(server, health=None, kubelet_watch=None,
                          intent_watch=None, sampler=None,
                          events=None) -> Registry:
    """Registry for a DevicePluginServer (tpukube.plugin.server); pass
    the daemon's HealthWatcher / KubeletSessionWatcher /
    AllocIntentWatcher to export their transition counters (a flat
    watch-events counter while pods bind means intent steering is dead
    and the kubelet is choosing chips unguided). ``sampler`` is the
    telemetry HealthSampler (obs/health.py): per-chip health / HBM /
    duty-cycle gauges and ICI-link-error counters, one series per chip.
    The telemetry families are NEW and opt into ``# HELP`` text; every
    legacy family stays byte-identical (no HELP)."""
    from tpukube.obs.statusz import device_health_counts

    reg = Registry()
    reg.counter("tpukube_plugin_allocations_total",
                fn=lambda: server.allocation_count)
    devices = reg.gauge("tpukube_plugin_devices")
    devices.labels(health="Healthy").set_function(
        lambda: device_health_counts(server._device)[0])
    devices.labels(health="Unhealthy").set_function(
        lambda: device_health_counts(server._device)[1])
    info = reg.gauge("tpukube_plugin_resource_info", emit_type=False)
    info.labels(resource=server.resource_name).set(1)
    # operators alarm on table-fallback nodes: their HBM/core facts are
    # static guesses, not runtime truth
    reg.gauge("tpukube_plugin_inventory_source").labels(
        source=server._device.inventory_source()
    ).set(1)
    reg.gauge("tpukube_plugin_intent_depth",
              fn=lambda: server.intents.depth())
    reg.counter("tpukube_plugin_divergences_total",
                fn=lambda: server.divergences)
    if health is not None:
        reg.counter("tpukube_plugin_health_transitions_total",
                    fn=lambda: health.transitions)
    if kubelet_watch is not None:
        reg.counter("tpukube_plugin_reregistrations_total",
                    fn=lambda: kubelet_watch.reregistrations)
        # the registration retrier's counters (unified retry layer)
        _add_retry_metrics(
            reg, retriers=[getattr(kubelet_watch, "retrier", None)]
        )
    if intent_watch is not None:
        reg.counter("tpukube_plugin_intent_watch_events_total",
                    fn=lambda: intent_watch.watch_events)
    if sampler is not None:
        _add_telemetry_metrics(reg, sampler)
    if events is not None:
        _add_events_counter(reg, events)
    return reg


def _add_snapshot_metrics(reg: Registry, snapshots) -> None:
    """Scheduling-snapshot cache families (sched/snapshot.py), shared
    by every renderer that exposes a SnapshotCache — the extender's
    main /metrics and its probe-port listener both build through here,
    so the series shapes can never drift apart. A flat hits counter
    under webhook load means every cycle is rebuilding (an epoch bump
    on a read path, or a mutation storm) — the regression this cache
    exists to prevent."""
    reg.counter(
        "tpukube_snapshot_rebuilds_total",
        fn=lambda: snapshots.rebuilds,
        help_text="Scheduling-snapshot rebuilds (one per ledger/"
                  "reservation epoch actually consulted).")
    reg.counter(
        "tpukube_snapshot_hits_total",
        fn=lambda: snapshots.hits,
        help_text="Snapshot lookups answered from the epoch cache "
                  "without re-deriving grids from the ledger.")
    reg.summary(
        "tpukube_snapshot_rebuild_seconds",
        quantiles=(0.5, 0.99),
        values_fn=snapshots.rebuild_seconds_snapshot,
        help_text="Wall time of snapshot rebuilds (coord-set capture; "
                  "sweep tables build lazily on first query).")
    if getattr(snapshots, "delta_enabled", False):
        # incremental-maintenance series render only while the feature
        # is on — with snapshot_delta_enabled=false the exposition is
        # byte-identical to the rebuild-every-epoch daemon's
        reg.counter(
            "tpukube_snapshot_delta_applies_total",
            fn=lambda: snapshots.delta_applies,
            help_text="Snapshot advances served by applying the queued "
                      "SnapshotDeltas (O(Δ)) instead of rebuilding "
                      "O(chips) from the ledger.")
        reg.counter(
            "tpukube_snapshot_delta_overflows_total",
            fn=lambda: snapshots.delta_overflows,
            help_text="Advances the delta log could not cover (bound "
                      "overflow or an unnoted bump) — each fell back "
                      "to a full rebuild. A growing rate means the log "
                      "bound trails the batch depth.")
        reg.summary(
            "tpukube_snapshot_delta_apply_seconds",
            quantiles=(0.5, 0.99),
            values_fn=snapshots.delta_apply_seconds_snapshot,
            help_text="Wall time of O(Δ) delta advances (one sample "
                      "per advance, covering every queued delta).")
    if getattr(snapshots, "audit_rate", 0.0) > 0.0:
        # audit-sentinel series render only when the sentinel is on
        # (snapshot_audit_rate > 0) — legacy exposition byte-identical
        reg.counter(
            "tpukube_snapshot_audit_checks_total",
            fn=lambda: snapshots.audit_checks,
            help_text="Sampled cache-hit audits: snapshot rebuilt from "
                      "the ledger and compared against the cache.")
        reg.counter(
            "tpukube_snapshot_audit_divergence_total",
            fn=lambda: snapshots.audit_divergences,
            help_text="Audits that found the cached snapshot diverging "
                      "from the ledger — a mutation path missing an "
                      "epoch bump. Any nonzero value is a bug.")

    # all reads below go through observe(): a scrape must not count
    # its own lookups as cache hits (that self-traffic would mask the
    # flat-hits diagnostic described above)
    def _slice_fn(sid: str, compute):
        def get() -> float:
            ss = snapshots.observe().slices.get(sid)
            return float(compute(ss)) if ss is not None else 0.0
        return get

    frag = reg.gauge(
        "tpukube_slice_fragmentation",
        help_text="Free-space fragmentation per ICI slice: 1 - "
                  "(largest free box)/(free chips); 0 = one perfect "
                  "box, -> 1 as free space shatters.")
    largest = reg.gauge(
        "tpukube_slice_largest_free_box_chips",
        help_text="Volume of the largest fully-free contiguous box "
                  "per ICI slice — the biggest gang that could still "
                  "land without preemption.")
    for sid in snapshots.observe().slice_ids():
        frag.labels(slice=sid).set_function(
            _slice_fn(sid, lambda ss: ss.fragmentation()))
        largest.labels(slice=sid).set_function(
            _slice_fn(sid, lambda ss: ss.largest_free_box()))


def _add_journal_metrics(reg: Registry, journal) -> None:
    """Durable-state journal families (sched/journal.py): WAL append
    throughput and volume, checkpoint latency, and the recovery
    numbers operators alarm on (a recovery_seconds sample near the
    cold-rebuild wall means the checkpoint cadence — or the WAL bound
    — is not keeping the replay tail short)."""
    reg.counter(
        "tpukube_journal_appends_total",
        fn=lambda: journal.appends,
        help_text="WAL records appended (one per ledger/gang mutation "
                  "seam).")
    reg.counter(
        "tpukube_journal_bytes_total",
        fn=lambda: journal.bytes_total,
        help_text="Bytes written to the WAL (pre-rotation total).")
    reg.summary(
        "tpukube_checkpoint_seconds",
        quantiles=(0.5, 0.99),
        values_fn=journal.checkpoint_seconds_snapshot,
        help_text="Wall time of checkpoint writes (serialize + fsync + "
                  "atomic rename, on the journal's drain thread).")
    reg.summary(
        "tpukube_recovery_seconds",
        quantiles=(0.5,),
        values_fn=journal.recovery_seconds_snapshot,
        help_text="Wall time of journal recoveries (checkpoint load + "
                  "WAL replay + apiserver reconcile), one sample per "
                  "recovery this process ran.")
    reg.counter(
        "tpukube_recovery_replayed_deltas_total",
        fn=lambda: journal.replayed_total,
        help_text="WAL records replayed by recoveries — the Δ in the "
                  "O(Δ-since-checkpoint) restart story.")


def _add_cycle_metrics(reg: Registry, cycle) -> None:
    """Batched-scheduling-cycle families (sched/cycle.py): throughput
    counters (``rate(tpukube_cycle_pods_planned_total)`` is the
    pods-scheduled/sec dashboard panel), the plan-hit/miss split whose
    ratio /statusz reports, batch-size and cycle-wall distributions.
    A flat hits counter with batching on means webhooks are not finding
    their plans — the re-planning regression batching exists to kill."""
    reg.counter(
        "tpukube_cycles_total",
        fn=lambda: cycle.cycles,
        help_text="Batch scheduling cycles run (one snapshot pin and "
                  "one queue drain each).")
    reg.counter(
        "tpukube_cycle_pods_planned_total",
        fn=lambda: cycle.pods_planned,
        help_text="Pods planned by batch cycles; its rate is "
                  "pods-scheduled/sec.")
    reg.counter(
        "tpukube_cycle_plan_hits_total",
        fn=lambda: cycle.plan_hits,
        help_text="Webhooks answered from the batch plan (a lookup, "
                  "not a re-plan).")
    reg.counter(
        "tpukube_cycle_plan_misses_total",
        fn=lambda: cycle.plan_misses,
        help_text="Webhooks the plan could not answer (fresh pod, "
                  "changed node set, deferred preemption) — the "
                  "legacy per-pod path served them.")
    reg.counter(
        "tpukube_cycle_assumes_total",
        fn=lambda: cycle.assumes,
        help_text="Placements committed as assumed allocations at plan "
                  "time (consumed — or undone — by /bind).")
    reg.summary(
        "tpukube_cycle_batch_size",
        quantiles=(0.5, 0.99),
        values_fn=lambda: list(cycle.batch_sizes),
        help_text="Pods planned per cycle (recent window).")
    reg.summary(
        "tpukube_cycle_wall_seconds",
        quantiles=(0.5, 0.99),
        values_fn=lambda: list(cycle.cycle_walls),
        help_text="Wall time per batch cycle (recent window; the "
                  "_bucket histogram is cumulative).")
    # the cumulative histogram the summary's window flattens
    reg.register(cycle.cycle_hist)
    # queue-age distribution (ISSUE 17): every planned pod's
    # admitted-to-planned age — the starvation signal /statusz
    # windows, now alertable as _bucket series
    reg.register(cycle.queue_age_hist)
    reg.gauge(
        "tpukube_cycle_queue_depth",
        fn=lambda: cycle.queue_depth(),
        help_text="Pending pods admitted to the scheduling queue but "
                  "not yet planned.")


def _add_tenant_metrics(reg: Registry, tenants) -> None:
    """Per-tenant serving-plane families (tpukube/tenancy): usage and
    dominant shares from the epoch-cached TenantLedger, quota caps,
    and the shed/denial counters the admission gate maintains. One
    child per tenant the plane knows (quota'd, with usage, or already
    refused); renderers rebuild per scrape so late tenants appear on
    the next pull."""
    names = tenants.known_tenants()

    chips = reg.gauge(
        "tpukube_tenant_chips_used",
        help_text="Whole-chip equivalents held per tenant (vTPU "
                  "shares count 1/n; gang reservations included).")
    hbm = reg.gauge(
        "tpukube_tenant_hbm_used_bytes",
        help_text="HBM bytes held per tenant.")
    share = reg.gauge(
        "tpukube_tenant_dominant_share",
        help_text="DRF dominant share per tenant: max(chips share, "
                  "HBM share) of cluster capacity.")
    q_chips = reg.gauge(
        "tpukube_tenant_quota_chips",
        help_text="Configured whole-chip quota per tenant (only "
                  "capped tenants render).")
    q_hbm = reg.gauge(
        "tpukube_tenant_quota_hbm_fraction",
        help_text="Configured HBM-fraction quota per tenant (only "
                  "capped tenants render).")
    shed_c = reg.counter(
        "tpukube_tenant_sheds_total",
        help_text="Admissions shed per tenant while an SLO burned at "
                  "the page threshold (TenantAdmissionShed events).")
    denied_c = reg.counter(
        "tpukube_tenant_quota_denials_total",
        help_text="Admissions refused per tenant for quota breaches "
                  "(TenantQuotaDenied events).")

    def usage_fn(tenant: str, attr: str):
        def get() -> float:
            u = tenants.ledger.usage().usage.get(tenant)
            return float(getattr(u, attr)) if u is not None else 0.0
        return get

    for t in names:
        chips.labels(tenant=t).set_function(usage_fn(t, "chips"))
        hbm.labels(tenant=t).set_function(usage_fn(t, "hbm_bytes"))
        share.labels(tenant=t).set_function(
            lambda t=t: tenants.ledger.usage().dominant_share(t))
        quota = tenants.quotas.get(t)
        if quota is not None and quota.chips is not None:
            q_chips.labels(tenant=t).set(quota.chips)
        if quota is not None and quota.hbm_fraction is not None:
            q_hbm.labels(tenant=t).set(quota.hbm_fraction)
        shed_c.labels(tenant=t).set_function(
            lambda t=t: tenants.counter_snapshot()[0].get(t, 0))
        denied_c.labels(tenant=t).set_function(
            lambda t=t: tenants.counter_snapshot()[1].get(t, 0))

    # per-tenant latency histograms (tenancy v2): admission (filter)
    # and commit (bind) walls, observed by the extender per decision —
    # the admission family is also the per-tenant burn source
    reg.register(tenants.admission_hist)
    reg.register(tenants.commit_hist)

    burn = reg.gauge(
        "tpukube_tenancy_burn_rate",
        help_text="Last evaluated SLO burn rate per source feeding "
                  "the shedding decision (sliding window).")
    for name in tenants.burn.stats()["sources"]:
        burn.labels(slo=name).set_function(
            lambda n=name: tenants.burn.stats()["last_burns"].get(n)
            or 0.0)
    tburn = reg.gauge(
        "tpukube_tenant_slo_burn",
        help_text="Last evaluated per-tenant windowed SLO burn — the "
                  "tenant-local number a shed decision cites.")
    bstats = tenants.burn.stats()
    for tenant, burns in sorted(bstats["last_tenant_burns"].items()):
        for slo in sorted(burns):
            tburn.labels(tenant=tenant, slo=slo).set_function(
                lambda t=tenant, s=slo:
                tenants.burn.last_tenant_burn(t, s))
    reg.gauge(
        "tpukube_tenancy_shedding",
        # read-only view of the last admission-path evaluation: a
        # scrape must not slide the burn windows itself
        fn=lambda: 1.0 if tenants.burn.last_page_burning() else 0.0,
        help_text="1 while SLO burn is at the page threshold and "
                  "over-share low-priority admissions are being shed.")


def _add_decision_metrics(reg: Registry, extender, decisions) -> None:
    """Decision-provenance families (obs/decisions.py): recording
    volume, the measured record overhead (the scenario-12 guard's
    numerator), and the cycle phase histogram — queue / pin / plan /
    answer / commit wall, the attribution layer for the webhook-answer
    p99 the O(fleet) roadmap item chases."""
    reg.counter(
        "tpukube_decisions_total",
        fn=lambda: decisions.recorded,
        help_text="Provenance stage events recorded (sampled pods "
                  "only).")
    reg.counter(
        "tpukube_decisions_record_seconds_total",
        fn=lambda: decisions.record_seconds,
        help_text="Cumulative wall spent recording provenance — the "
                  "measured overhead the check.sh decisions smoke "
                  "guards against a floor.")
    if extender.phase_hist is not None:
        reg.register(extender.phase_hist)


def _add_capacity_metrics(reg: Registry, capacity) -> None:
    """Capacity analytics families (obs/capacity.py): flight-recorder
    volume + measured overhead (the check.sh capacity smoke's
    numerator), the failed-plan taxonomy counter, and the live
    stranded ledger per root cause — the stranded-ratio recording rule
    and the fragmentation ticket alert read these."""
    from tpukube.obs.capacity import UNSCHEDULABLE_REASONS

    reg.counter(
        "tpukube_capacity_samples_total",
        fn=lambda: capacity.samples_taken,
        help_text="Flight-recorder fleet samples taken (scheduling "
                  "clock cadence).")
    reg.counter(
        "tpukube_capacity_sample_seconds_total",
        fn=lambda: capacity.sample_seconds,
        help_text="Cumulative wall spent sampling + classifying — the "
                  "measured overhead the check.sh capacity smoke "
                  "floors.")
    reg.gauge(
        "tpukube_capacity_fleet_chips",
        fn=lambda: capacity.fleet_chips,
        help_text="Fleet chip count at the last flight-recorder "
                  "sample (the stranded-ratio denominator).")
    reg.gauge(
        "tpukube_capacity_recoverable_chips",
        fn=lambda: capacity._recoverable_last,
        help_text="Chips a perfect repack would recover into the "
                  "largest contiguous boxes, from the last stranded "
                  "classification (the defragmenter's objective).")
    unsched = reg.counter(
        "tpukube_unschedulable_pods",
        help_text="Failed/deferred plans root-caused by reason "
                  "(fragmented = chips free but no contiguous box; "
                  "capacity = not enough free chips anywhere).")
    chips_g = reg.gauge(
        "tpukube_capacity_stranded_chips",
        help_text="Chips requested by live stranded demands, by root "
                  "cause (ledger entries expire with their demand).")
    demands_g = reg.gauge(
        "tpukube_capacity_stranded_demands",
        help_text="Live stranded demands (gangs collapse to one), by "
                  "root cause.")
    for reason in UNSCHEDULABLE_REASONS:
        unsched.labels(reason=reason).set_function(
            lambda r=reason: capacity.unschedulable_counts().get(r, 0))
        chips_g.labels(reason=reason).set_function(
            lambda r=reason:
            capacity.stranded_by_reason().get(r, (0, 0))[1])
        demands_g.labels(reason=reason).set_function(
            lambda r=reason:
            capacity.stranded_by_reason().get(r, (0, 0))[0])


def _add_drain_metrics(reg: Registry, drain) -> None:
    """Drain choreography families (sched/drain.py): lifecycle
    counters plus the disruption-budget gauge pair scenario 15 and the
    elasticity bench read (peak moves per tick vs the configured
    budget)."""
    reg.counter(
        "tpukube_drain_started_total",
        fn=lambda: drain.drains_started,
        help_text="Drains begun (cordon + record).")
    reg.counter(
        "tpukube_drain_completed_total",
        fn=lambda: drain.drains_completed,
        help_text="Drains whose nodes were fully un-ingested.")
    reg.counter(
        "tpukube_drain_evictions_total",
        fn=lambda: drain.evictions_total,
        help_text="Pods evicted by drain migrate-or-preempt ticks "
                  "(gangs dissolve all-or-nothing).")
    reg.counter(
        "tpukube_drain_nodes_removed_total",
        fn=lambda: drain.nodes_removed_total,
        help_text="Nodes un-ingested at drain completion (the "
                  "inverse of bulk ingest: one seam per batch).")
    reg.counter(
        "tpukube_drain_chips_removed_total",
        fn=lambda: drain.chips_removed_total,
        help_text="Chips decommissioned by completed drains.")
    reg.counter(
        "tpukube_drain_slices_dropped_total",
        fn=lambda: drain.slices_dropped_total,
        help_text="Slices whose last node left at drain completion.")
    reg.gauge(
        "tpukube_drain_peak_tick_moves",
        fn=lambda: drain.peak_tick_moves,
        help_text="Worst-ever workloads moved in one drain tick — "
                  "must never exceed the configured disruption "
                  "budget (drain_max_concurrent_moves).")
    reg.gauge(
        "tpukube_drain_active",
        fn=lambda: len(drain.statusz()["active"]),
        help_text="Drains currently in the migrate-or-preempt phase.")


def _add_autoscaler_metrics(reg: Registry, autoscaler) -> None:
    """Autoscaler loop families (sched/autoscale.py): scaling actions
    and evaluation volume — the elasticity bench's time-to-capacity
    numerator rides scale_ups/nodes_added."""
    reg.counter(
        "tpukube_autoscaler_scale_ups_total",
        fn=lambda: autoscaler.scale_ups,
        help_text="Scale-up actions (one provisioned slice each, "
                  "bulk-ingested as one decision).")
    reg.counter(
        "tpukube_autoscaler_scale_downs_total",
        fn=lambda: autoscaler.scale_downs,
        help_text="Scale-down actions (one graceful slice drain "
                  "each).")
    reg.counter(
        "tpukube_autoscaler_nodes_added_total",
        fn=lambda: autoscaler.nodes_added_total,
        help_text="Nodes successfully ingested by scale-ups.")
    reg.counter(
        "tpukube_autoscaler_ticks_total",
        fn=lambda: autoscaler.ticks,
        help_text="Scaling evaluations run (amortized onto the "
                  "decision path at cooldown cadence).")


def _add_retry_metrics(reg: Registry, retriers=(), circuits=()) -> None:
    """Retry/circuit families (core/retry.py), one child per named
    Retrier/CircuitBreaker — shared by both daemons' builders so the
    series shapes can never drift apart."""
    retriers = [r for r in retriers if r is not None]
    circuits = [c for c in circuits if c is not None]
    if retriers:
        attempts = reg.counter(
            "tpukube_retry_attempts_total",
            help_text="Call attempts made under the unified retry "
                      "policy, by operation.")
        retries = reg.counter(
            "tpukube_retry_retries_total",
            help_text="Attempts beyond the first (each one is a "
                      "transient failure that was retried).")
        exhausted = reg.counter(
            "tpukube_retry_exhausted_total",
            help_text="Calls that gave up after max attempts or the "
                      "overall deadline (RetryExhausted events).")
        for r in retriers:
            attempts.labels(op=r.name).set_function(
                lambda r=r: r.stats.attempts)
            retries.labels(op=r.name).set_function(
                lambda r=r: r.stats.retries)
            exhausted.labels(op=r.name).set_function(
                lambda r=r: r.stats.exhausted)
    if circuits:
        state = reg.gauge(
            "tpukube_circuit_state",
            help_text="Breaker state: 0 closed, 1 half-open, 2 open.")
        opens = reg.counter(
            "tpukube_circuit_opens_total",
            help_text="Times the breaker tripped open (CircuitOpen "
                      "events).")
        for c in circuits:
            state.labels(circuit=c.name).set_function(
                lambda c=c: c.state_code())
            opens.labels(circuit=c.name).set_function(
                lambda c=c: c.opens)


def _add_events_counter(reg: Registry, events) -> None:
    counter = reg.counter(
        "tpukube_events_total",
        help_text="Structured journal events by reason "
                  "(GangCommitted, ChipUnhealthy, ...).")
    # children for every reason seen so far; later reasons appear on
    # the next render (renderers rebuild per scrape)
    for reason in sorted(events.counts_by_reason()):
        counter.labels(reason=reason).set_function(
            lambda r=reason: events.counts_by_reason().get(r, 0))


def _add_telemetry_metrics(reg: Registry, sampler) -> None:
    """Per-chip telemetry families (pull-based over the sampler's latest
    samples; children exist for every chip the sampler has seen)."""
    healthy = reg.gauge(
        "tpukube_chip_healthy",
        help_text="1 while the chip serves traffic, 0 after a health "
                  "fault (per-chip ListAndWatch health).")
    duty = reg.gauge(
        "tpukube_chip_duty_cycle_percent",
        help_text="Instantaneous TensorCore duty cycle per chip "
                  "(synthesized on the sim backend).")
    hbm_used = reg.gauge(
        "tpukube_chip_hbm_used_bytes",
        help_text="HBM bytes in use per chip (synthesized on the sim "
                  "backend).")
    hbm_total = reg.gauge(
        "tpukube_chip_hbm_total_bytes",
        help_text="HBM capacity per chip.")
    link_errs = reg.counter(
        "tpukube_chip_ici_link_errors_total",
        help_text="Cumulative ICI link-error count per chip; a non-zero "
                  "rate means the chip is riding a degraded link.")
    flips = reg.counter(
        "tpukube_chip_health_transitions_total",
        help_text="Health-state transitions observed per chip "
                  "(healthy/degraded/unhealthy flips).")

    def field(did: str, attr: str, default: float = 0.0):
        def get() -> float:
            t = sampler.sample(did)
            return float(getattr(t, attr)) if t is not None else default
        return get

    for t in sampler.latest():
        did = t.device_id
        healthy.labels(chip=did).set_function(
            lambda d=did: 1.0 if (
                (s := sampler.sample(d)) is not None
                and s.state != "unhealthy"
            ) else 0.0
        )
        duty.labels(chip=did).set_function(
            field(did, "duty_cycle_percent"))
        hbm_used.labels(chip=did).set_function(field(did, "hbm_used_bytes"))
        hbm_total.labels(chip=did).set_function(
            field(did, "hbm_total_bytes"))
        link_errs.labels(chip=did).set_function(
            field(did, "ici_link_errors"))
        flips.labels(chip=did).set_function(
            lambda d=did: sampler.transition_count(d))
    chips = reg.gauge(
        "tpukube_node_chips",
        help_text="This node's chips by health state (healthy / "
                  "degraded = up but on a downed ICI link / unhealthy).")
    for state in ("healthy", "degraded", "unhealthy"):
        chips.labels(state=state).set_function(
            lambda s=state: sampler.state_counts().get(s, 0))
    reg.counter(
        "tpukube_telemetry_samples_total",
        fn=lambda: sampler.samples,
        help_text="Telemetry polls taken by the node agent's sampler.")


def render_plugin_metrics(server, health=None, kubelet_watch=None,
                          intent_watch=None, sampler=None,
                          events=None) -> str:
    """Prometheus text for a DevicePluginServer — see
    build_plugin_registry."""
    return build_plugin_registry(
        server, health=health, kubelet_watch=kubelet_watch,
        intent_watch=intent_watch, sampler=sampler, events=events,
    ).render()


def build_syncer_registry(syncer) -> Registry:
    reg = Registry()
    reg.counter("tpukube_syncer_syncs_total", fn=lambda: syncer.syncs)
    return reg


def render_syncer_metrics(syncer) -> str:
    """Prometheus text for a NodeAnnotationSyncer sidecar."""
    return build_syncer_registry(syncer).render()


class MetricsServer:
    """Minimal threaded HTTP server for the node agent: /metrics always,
    /statusz when a ``statusz`` document callback is wired (the node
    agent passes tpukube.obs.statusz.plugin_statusz)."""

    def __init__(self, render: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0,
                 statusz: Optional[Callable[[], Any]] = None):
        render_fn = render
        statusz_fn = statusz

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, body: bytes, content_type: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802  (http.server API)
                if self.path == "/metrics":
                    self._reply(
                        render_fn().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/statusz" and statusz_fn is not None:
                    self._reply(
                        json.dumps(statusz_fn(), sort_keys=True).encode(),
                        "application/json",
                    )
                else:
                    self.send_error(404)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tpukube-metrics",
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

"""Metrics export (SURVEY.md §6 "Metrics / logging / observability").

The reference lineage only has glog; BASELINE's north-star metrics demand
more: cluster TPU-chip utilization % and the gang-schedule latency
distribution. This module renders Prometheus text-format metrics without
depending on prometheus_client (not in this environment), and provides a
tiny threaded HTTP server for the node agent (the extender serves /metrics
from its aiohttp app).

Exported series (extender):
  tpu_chip_utilization_percent            — north star #1
  gang_schedule_latency_seconds{quantile} — north star #2 (+ _count/_sum)
  tpukube_binds_total, tpukube_gang_rollbacks_total,
  tpukube_preemptions_total, tpukube_webhook_latency_seconds{handler,quantile}

Exported series (node agent):
  tpukube_plugin_allocations_total, tpukube_plugin_devices{health}
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable, Optional


def quantile(values: Iterable[float], q: float) -> float:
    """Nearest-rank quantile; 0.0 on empty input."""
    vs = sorted(values)
    if not vs:
        return 0.0
    idx = min(len(vs) - 1, max(0, round(q * (len(vs) - 1))))
    return vs[idx]


def _esc(value: str) -> str:
    """Prometheus text-format label-value escaping. Label values here can
    carry arbitrary runtime text (e.g. inventory_source embeds PJRT error
    messages); an unescaped quote or newline would corrupt the whole
    scrape — on exactly the degraded nodes the metric exists to flag."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(name: str, value: float, labels: Optional[dict[str, str]] = None) -> str:
    if labels:
        inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {value:.6g}\n"
    return f"{name} {value:.6g}\n"


def render_extender_metrics(extender, reconcile=None, evictions=None,
                            node_refresh=None, lifecycle=None) -> str:
    """Prometheus text for an Extender (tpukube.sched.extender); pass the
    daemon's AllocReconcileLoop / EvictionExecutor /
    NodeTopologyRefreshLoop / PodLifecycleReleaseLoop to export their
    counters (the divergence/reconcile/eviction/release story operators
    alarm on — a flat releases counter under churn means the release
    watch is dead and chips are leaking)."""
    out: list[str] = []
    out.append("# TYPE tpu_chip_utilization_percent gauge\n")
    out.append(_fmt("tpu_chip_utilization_percent",
                    100.0 * extender.state.utilization()))

    lats = list(extender.gang.commit_latencies)
    out.append("# TYPE gang_schedule_latency_seconds summary\n")
    for q in (0.5, 0.9, 0.99):
        out.append(_fmt("gang_schedule_latency_seconds", quantile(lats, q),
                        {"quantile": str(q)}))
    out.append(_fmt("gang_schedule_latency_seconds_count", len(lats)))
    out.append(_fmt("gang_schedule_latency_seconds_sum", sum(lats)))

    out.append("# TYPE tpukube_ici_links_down gauge\n")
    out.append(_fmt("tpukube_ici_links_down", sum(
        len(extender.state.broken_links(sid))
        for sid in extender.state.slice_ids()
    )))

    out.append("# TYPE tpukube_binds_total counter\n")
    out.append(_fmt("tpukube_binds_total", extender.binds_total))
    out.append("# TYPE tpukube_gang_rollbacks_total counter\n")
    out.append(_fmt("tpukube_gang_rollbacks_total", extender.gang.rollbacks))
    out.append("# TYPE tpukube_preemptions_total counter\n")
    out.append(_fmt("tpukube_preemptions_total", extender.preemptions))

    out.append("# TYPE tpukube_webhook_latency_seconds summary\n")
    for handler, window in extender.latencies.items():
        vs = list(window)
        for q in (0.5, 0.99):
            out.append(_fmt("tpukube_webhook_latency_seconds",
                            quantile(vs, q),
                            {"handler": handler, "quantile": str(q)}))

    # evicted-but-unconfirmed preemption victims: non-zero means gang
    # binds are gated on graceful terminations in progress
    out.append("# TYPE tpukube_gang_victims_terminating gauge\n")
    out.append(_fmt("tpukube_gang_victims_terminating",
                    extender.gang.terminating_count()))

    out.append("# TYPE tpukube_evictions_pending gauge\n")
    if evictions is not None:
        out.append(_fmt("tpukube_evictions_pending", evictions.depth()))
        out.append("# TYPE tpukube_evictions_total counter\n")
        out.append(_fmt("tpukube_evictions_total", evictions.evicted))
        out.append("# TYPE tpukube_evictions_blocked_total counter\n")
        out.append(_fmt("tpukube_evictions_blocked_total", evictions.blocked))
        out.append("# TYPE tpukube_eviction_failures_total counter\n")
        out.append(_fmt("tpukube_eviction_failures_total", evictions.failures))
        # a PDB-wedged eviction is a capacity leak in progress: alarm on
        # age, not just depth
        out.append("# TYPE tpukube_eviction_oldest_age_seconds gauge\n")
        out.append(_fmt("tpukube_eviction_oldest_age_seconds",
                        evictions.oldest_age_seconds()))
    else:
        # no executor (sim/dev): the queue depth is still the operator's
        # double-allocation early-warning
        out.append(_fmt("tpukube_evictions_pending",
                        len(extender.pending_evictions)))
    if reconcile is not None:
        out.append("# TYPE tpukube_reconciles_total counter\n")
        out.append(_fmt("tpukube_reconciles_total", reconcile.reconciled))
    if node_refresh is not None:
        out.append("# TYPE tpukube_node_refreshes_total counter\n")
        out.append(_fmt("tpukube_node_refreshes_total",
                        node_refresh.refreshed))
    if lifecycle is not None:
        out.append("# TYPE tpukube_lifecycle_releases_total counter\n")
        out.append(_fmt("tpukube_lifecycle_releases_total",
                        lifecycle.released))
    return "".join(out)


def render_plugin_metrics(server, health=None, kubelet_watch=None,
                          intent_watch=None) -> str:
    """Prometheus text for a DevicePluginServer (tpukube.plugin.server);
    pass the daemon's HealthWatcher / KubeletSessionWatcher /
    AllocIntentWatcher to export their transition counters (a flat
    watch-events counter while pods bind means intent steering is dead
    and the kubelet is choosing chips unguided)."""
    out: list[str] = []
    out.append("# TYPE tpukube_plugin_allocations_total counter\n")
    out.append(_fmt("tpukube_plugin_allocations_total", server.allocation_count))
    out.append("# TYPE tpukube_plugin_devices gauge\n")
    healthy = unhealthy = 0
    for _, h in server._device.device_list():
        if h.value == "Healthy":
            healthy += 1
        else:
            unhealthy += 1
    out.append(_fmt("tpukube_plugin_devices", healthy, {"health": "Healthy"}))
    out.append(_fmt("tpukube_plugin_devices", unhealthy, {"health": "Unhealthy"}))
    out.append(_fmt("tpukube_plugin_resource_info", 1,
                    {"resource": server.resource_name}))
    # operators alarm on table-fallback nodes: their HBM/core facts are
    # static guesses, not runtime truth
    out.append("# TYPE tpukube_plugin_inventory_source gauge\n")
    out.append(_fmt("tpukube_plugin_inventory_source", 1,
                    {"source": server._device.inventory_source()}))
    out.append("# TYPE tpukube_plugin_intent_depth gauge\n")
    out.append(_fmt("tpukube_plugin_intent_depth", server.intents.depth()))
    out.append("# TYPE tpukube_plugin_divergences_total counter\n")
    out.append(_fmt("tpukube_plugin_divergences_total", server.divergences))
    if health is not None:
        out.append("# TYPE tpukube_plugin_health_transitions_total counter\n")
        out.append(_fmt("tpukube_plugin_health_transitions_total",
                        health.transitions))
    if kubelet_watch is not None:
        out.append("# TYPE tpukube_plugin_reregistrations_total counter\n")
        out.append(_fmt("tpukube_plugin_reregistrations_total",
                        kubelet_watch.reregistrations))
    if intent_watch is not None:
        out.append("# TYPE tpukube_plugin_intent_watch_events_total counter\n")
        out.append(_fmt("tpukube_plugin_intent_watch_events_total",
                        intent_watch.watch_events))
    return "".join(out)


def render_syncer_metrics(syncer) -> str:
    """Prometheus text for a NodeAnnotationSyncer sidecar."""
    return (
        "# TYPE tpukube_syncer_syncs_total counter\n"
        + _fmt("tpukube_syncer_syncs_total", syncer.syncs)
    )


class MetricsServer:
    """Minimal threaded /metrics HTTP server for the node agent."""

    def __init__(self, render: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0):
        render_fn = render

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802  (http.server API)
                if self.path != "/metrics":
                    self.send_error(404)
                    return
                body = render_fn().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tpukube-metrics",
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

"""Shard worker daemon — one planner replica as its own process.

The process-parallel sharded control plane (ISSUE 14) runs each
:class:`~tpukube.sched.shard.PlannerReplica` as a real OS process: a
plain :class:`~tpukube.sched.extender.Extender` (``planner_replicas``
forced to 1 — a worker IS one planner, never a router) serving

  * the standard extender webhook app (``make_app``: /filter,
    /prioritize, /bind, /healthz, /metrics, /state/*, /statusz) —
    the worker is a ``main_extender``-style daemon, and
  * the ``/worker/*`` routes below — the replica half of the
    :class:`~tpukube.sched.shard.SubprocessTransport` contract: batch
    admit/plan/bind for the driver path, gauges + gang prepare for the
    router's two-phase rendezvous, summary/allocs for the federated
    read views, eviction drain, and FakeClock advance.

Every /worker route dispatches into the SAME replica-side helpers the
in-process transport calls directly (``shard.replica_gauges``,
``shard.gang_prepare_part``, ...) — the transport changes the wire,
never the computation, which is what makes the process-mode N=1
placement parity a structural property rather than a coincidence.

The router spawns workers via ``tpukube.cli shard-worker`` (a resolved
per-replica YAML is the ONE config source; the spawn scrubs TPUKUBE_*
env so an inherited ``TPUKUBE_PLANNER_REPLICAS`` cannot make a worker
try to be a router). In production the same daemon shape runs as one
Deployment per replica behind the router webhook front — see
deploy/README's multi-daemon sketch.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Optional

from aiohttp import web

from tpukube import trace as trace_mod
from tpukube.core import codec
from tpukube.sched import kube, shard, wirecodec
from tpukube.sched.extender import Extender, make_app
from tpukube.sched.gang import GangError
from tpukube.sched.state import StateError

log = logging.getLogger("tpukube.shardworker")


#: batched transport bodies (a 10k-node fleet upsert, a 2k-pod admit
#: wave, a rebuild feed) far exceed aiohttp's 1 MiB default cap
CLIENT_MAX_SIZE = 1 << 30


def make_worker_app(extender: Extender, clock=None) -> web.Application:
    """The worker daemon's app: the full extender webhook surface plus
    the /worker/* transport routes."""
    app = make_app(extender, client_max_size=CLIENT_MAX_SIZE)

    @web.middleware
    async def trace_context_mw(request: web.Request, handler):
        # the router stamps X-Tpukube-Trace: <trace>/<parent span> on
        # every fanned request; expose it through the TRACE_CONTEXT
        # contextvar for the request's duration so the replica-local
        # DecisionTrace / DecisionLog records tag themselves with the
        # router's trace — the join key the merged timeline and the
        # stitched /explain use. No header (an unsharded deployment, a
        # kubelet probe) → the contextvar stays None and the records
        # are byte-identical to the unsharded ones (off-is-off).
        hdr = request.headers.get("X-Tpukube-Trace")
        if not hdr:
            return await handler(request)
        trace_id, _, parent = hdr.partition("/")
        tok = trace_mod.TRACE_CONTEXT.set(
            {"trace": trace_id, "parent": parent})
        try:
            return await handler(request)
        finally:
            trace_mod.TRACE_CONTEXT.reset(tok)

    app.middlewares.append(trace_context_mw)

    # Wire codec (ISSUE 20, sched/wirecodec.py). The worker side is
    # CAPABILITY-driven, not config-driven: it decodes whatever
    # Content-Type the router sent and answers TKW1 only when the
    # request's Accept asked for it — its own YAML (which the router
    # pins to wire_codec-agnostic inprocess anyway) never gates the
    # wire format, so a binary router and a JSON router can share a
    # worker mid rolling upgrade. wire_compress_min_bytes DOES come
    # from config: both ends compress by the same threshold.
    compress_min = extender._config.wire_compress_min_bytes

    def _dumps(obj: Any) -> str:
        # compact separators on the JSON path too (journal.py already
        # does this) — a few percent off every wire body, codec off
        return json.dumps(obj, separators=wirecodec.JSON_SEPARATORS)

    async def _body(request: web.Request) -> Any:
        ct = request.headers.get("Content-Type", "")
        if ct.split(";", 1)[0].strip() == wirecodec.WIRE_CONTENT_TYPE:
            raw = await request.read()
            try:
                return wirecodec.decode_frame(raw)
            except wirecodec.WireCodecError as e:
                # a truncated/corrupt frame is the CALLER's defect:
                # answer 400 and keep serving — never crash the
                # replica, never let the router read it as death
                raise web.HTTPBadRequest(text=f"bad wire frame: {e}")
        try:
            return await request.json()
        except json.JSONDecodeError as e:
            raise web.HTTPBadRequest(text=f"bad JSON: {e}")

    def _respond(request: web.Request, obj: Any) -> web.Response:
        if wirecodec.WIRE_CONTENT_TYPE in \
                request.headers.get("Accept", ""):
            frame, _ = wirecodec.encode_frame(obj, compress_min)
            return web.Response(
                body=frame,
                content_type=wirecodec.WIRE_CONTENT_TYPE)
        return web.json_response(obj, dumps=_dumps)

    async def handle(request: web.Request) -> web.Response:
        doc = await _body(request)
        try:
            out = extender.handle(doc["kind"], doc["body"])
        except kube.KubeSchemaError as e:
            # in-band so the router re-raises the SAME exception type
            # the in-process transport would have propagated
            return _respond(request, {"schema_error": str(e)})
        return _respond(request, out)

    async def upsert(request: web.Request) -> web.Response:
        doc = await _body(request)
        # ONE bulk-ingest decision for the whole batch (ISSUE 15): the
        # worker ingests its shard through the cold-start fast path
        return _respond(request, {
            "results": extender.upsert_nodes_many(doc["items"])
        })

    async def admit(request: web.Request) -> web.Response:
        doc = await _body(request)
        admitted = []
        for obj in doc["pods"]:
            try:
                admitted.append(bool(extender.admit(
                    kube.pod_from_k8s(obj)
                )))
            except kube.KubeSchemaError as e:
                log.error("admit: undecodable pod object (%s)", e)
                admitted.append(False)
        return _respond(request, {"admitted": admitted})

    async def plan(request: web.Request) -> web.Response:
        return _respond(request, {"planned": extender.plan_pending()})

    async def planned(request: web.Request) -> web.Response:
        doc = await _body(request)
        return _respond(request, {"nodes": {
            key: extender.planned_node(key) for key in doc["keys"]
        }})

    async def bind_many(request: web.Request) -> web.Response:
        doc = await _body(request)
        results = []
        for body in doc["bodies"]:
            try:
                results.append(extender.handle("bind", body))
            except kube.KubeSchemaError as e:
                results.append(kube.binding_result(
                    f"bad bind body: {e}"
                ))
        return _respond(request, {"results": results})

    async def release_many(request: web.Request) -> web.Response:
        doc = await _body(request)
        for key in doc["keys"]:
            extender.handle("release", {"pod_key": key})
        return _respond(request, {})

    async def gauges(request: web.Request) -> web.Response:
        return _respond(request, 
            {"slices": shard.replica_gauges(extender)}
        )

    async def gang(request: web.Request) -> web.Response:
        doc = await _body(request)
        op = doc.get("op")
        try:
            if op == "fit":
                pod = kube.pod_from_k8s(doc["pod"])
                return _respond(request, {"fits": shard.gang_fit_probe(
                    extender, pod, int(doc["total"])
                )})
            if op == "prepare":
                pod = kube.pod_from_k8s(doc["pod"])
                parts = shard.gang_prepare_part(
                    extender, pod, int(doc["cpp"]),
                    {sid: int(v)
                     for sid, v in doc["volumes"].items()},
                )
                return _respond(request, {"parts": parts})
            key = (doc["namespace"], doc["name"]) \
                if "namespace" in doc else None
            if op == "drop":
                extender.gang.drop_reservation(key)
                return _respond(request, {})
            if op == "dissolve":
                extender.gang.dissolve(key)
                return _respond(request, {})
            if op == "reservation":
                res = extender.gang.reservation(*key)
                return _respond(request, {"reservation": (
                    None if res is None else {
                        "committed": res.committed,
                        "slices": {
                            sid: sorted(coords)
                            for sid, coords in
                            res.slice_coords.items()
                        },
                    }
                )})
            if op == "sweep":
                extender.gang.sweep()
                return _respond(request, {})
        except GangError as e:
            return _respond(request, {"error": str(e), "kind": "gang"})
        except StateError as e:
            return _respond(request, {"error": str(e), "kind": "state"})
        raise web.HTTPBadRequest(text=f"unknown gang op {op!r}")

    async def allocs(request: web.Request) -> web.Response:
        return _respond(request, {"allocs": [
            codec.alloc_obj(a) for a in extender.state.allocations()
        ]})

    async def allocs_since(request: web.Request) -> web.Response:
        # generation-based incremental resync (ISSUE 15): a churn
        # wave's federated read moves O(changed-allocs) bytes per
        # replica instead of the whole ledger
        doc = await _body(request)
        out = extender.state.allocs_since(doc.get("cursor"))
        if out is None:
            return _respond(request, {"disabled": True})
        wire: dict = {"cursor": list(out["cursor"]),
                      "bytes": out["bytes"]}
        if "full" in out:
            wire["full"] = [codec.alloc_obj(a) for a in out["full"]]
        else:
            wire["adds"] = [codec.alloc_obj(a) for a in out["adds"]]
            wire["removes"] = out["removes"]
        return _respond(request, wire)

    async def recover(request: web.Request) -> web.Response:
        # warm restart from this worker's own journal segment,
        # reconciled against the router-provided node/pod truth
        # (ROADMAP sharding item (d)); an error answer tells the
        # router to fall back to the cold re-ingest on a fresh daemon
        from tpukube.sched import journal as journal_mod

        doc = await _body(request)
        if extender.journal is None:
            return _respond(request, 
                {"recover_error": "journal disabled"})
        try:
            stats = journal_mod.recover_extender(
                extender,
                shard._ListApi(doc.get("nodes") or [],
                               doc.get("pods") or []),
            )
        except journal_mod.JournalError as e:
            return _respond(request, {"recover_error": str(e)})
        return _respond(request, {
            "stats": stats,
            "restored": len(extender.state.allocations()),
        })

    async def alloc_one(request: web.Request) -> web.Response:
        pod = request.query.get("pod", "")
        a = extender.state.allocation(pod)
        return _respond(request, 
            {"alloc": codec.alloc_obj(a) if a is not None else None}
        )

    async def nodes(request: web.Request) -> web.Response:
        return _respond(request, 
            {"names": list(extender.state.node_names())}
        )

    async def summary(request: web.Request) -> web.Response:
        return _respond(request, shard.replica_summary(extender))

    async def emit(request: web.Request) -> web.Response:
        doc = await _body(request)
        extender.events.emit(
            doc.get("reason", ""), obj=doc.get("obj", ""),
            message=doc.get("message", ""),
            **({"type": doc["type"]} if doc.get("type") else {}),
        )
        return _respond(request, {})

    async def rebuild(request: web.Request) -> web.Response:
        doc = await _body(request)
        return _respond(request, 
            {"restored": extender.rebuild_from_pods(doc["pods"])}
        )

    async def evictions(request: web.Request) -> web.Response:
        out: list[str] = []
        q = extender.pending_evictions
        while True:
            try:
                out.append(q.popleft())
            except IndexError:
                break
        return _respond(request, {"pods": out})

    async def stall(request: web.Request) -> web.Response:
        # test-only: hold this request open for N seconds without
        # blocking the worker loop — the router's fan-out concurrency
        # proof (tests/test_shard_proc.py) measures overlap with it
        import asyncio

        doc = await _body(request)
        await asyncio.sleep(min(float(doc.get("seconds", 0)), 5.0))
        return _respond(request, {})

    async def advance(request: web.Request) -> web.Response:
        doc = await _body(request)
        adv = getattr(clock, "advance", None)
        if adv is None:
            raise web.HTTPBadRequest(
                text="worker runs the system clock (spawn with "
                     "--fake-clock to advance simulated time)"
            )
        adv(float(doc["seconds"]))
        return _respond(request, {"now": clock.monotonic()})

    app.router.add_post("/worker/handle", handle)
    app.router.add_post("/worker/upsert", upsert)
    app.router.add_post("/worker/admit", admit)
    app.router.add_post("/worker/plan", plan)
    app.router.add_post("/worker/planned", planned)
    app.router.add_post("/worker/bind", bind_many)
    app.router.add_post("/worker/release", release_many)
    app.router.add_get("/worker/gauges", gauges)
    app.router.add_post("/worker/gang", gang)
    app.router.add_get("/worker/allocs", allocs)
    app.router.add_post("/worker/allocs_since", allocs_since)
    app.router.add_post("/worker/recover", recover)
    app.router.add_get("/worker/alloc", alloc_one)
    app.router.add_get("/worker/nodes", nodes)
    app.router.add_get("/worker/summary", summary)
    app.router.add_post("/worker/emit", emit)
    app.router.add_post("/worker/rebuild", rebuild)
    app.router.add_post("/worker/evictions", evictions)
    app.router.add_post("/worker/advance", advance)
    app.router.add_post("/worker/stall", stall)
    return app


def make_router_app(router) -> web.Application:
    """The router's federated observability listener (ISSUE 16): the
    aggregation half of the sharded control plane. /metrics renders
    every worker registry merged under a ``replica`` label plus the
    router-local series; /explain stitches the router's own
    route/spillover/rendezvous stages with the owning replicas'
    chains; /events merges the worker journals with replica
    attribution; /statusz carries the wire bill and the flight
    recorder. Webhook traffic does NOT flow here — this listener is
    observability-only (serve it with
    :func:`tpukube.sched.extender.run_probe_server`). The fan-outs
    behind these routes are blocking HTTP round-trips, so every
    handler hops to a thread: a slow replica must not stall the
    listener's own /healthz."""
    import asyncio

    app = web.Application()

    async def healthz(request: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def metrics(request: web.Request) -> web.Response:
        from tpukube.metrics import render_federated_metrics

        text = await asyncio.to_thread(render_federated_metrics, router)
        return web.Response(text=text, content_type="text/plain")

    async def statusz(request: web.Request) -> web.Response:
        from tpukube.obs.statusz import router_statusz

        return web.json_response(
            await asyncio.to_thread(router_statusz, router))

    async def explain(request: web.Request) -> web.Response:
        pod = request.query.get("pod", "")
        if not pod:
            raise web.HTTPBadRequest(text="missing ?pod=<ns/name>")
        doc = await asyncio.to_thread(router.explain, pod)
        if doc is None:
            raise web.HTTPNotFound(
                text="decision provenance is disabled "
                     "(decisions_enabled: false)")
        return web.json_response(doc)

    async def events(request: web.Request) -> web.Response:
        q = request.query
        since = q.get("since")
        rows = await asyncio.to_thread(
            lambda: router.events_federated(
                reason=q.get("reason"), pod=q.get("pod"),
                node=q.get("node"),
                since=float(since) if since else None,
                replica=q.get("replica"),
            )
        )
        return web.json_response(rows)

    async def trace_route(request: web.Request) -> web.Response:
        if router.trace is None:
            raise web.HTTPNotFound(text="router tracing disabled")
        since = int(request.query.get("since", 0))
        return web.json_response(router.trace.events(since_seq=since))

    async def capacity(request: web.Request) -> web.Response:
        from tpukube.obs.capacity import parse_since

        raw = request.query.get("since")
        try:
            since = parse_since(raw) if raw else None
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e)) from None
        doc = await asyncio.to_thread(router.capacity_doc, since)
        if doc is None:
            raise web.HTTPNotFound(
                text="capacity analytics disabled "
                     "(set capacity_enabled)")
        return web.json_response(doc)

    async def capacity_probe(request: web.Request) -> web.Response:
        from tpukube.obs.capacity import parse_shape

        q = request.query
        try:
            count = int(q["count"]) if "count" in q else None
            shape = (parse_shape(q["shape"]) if "shape" in q
                     else None)
            cpp = int(q.get("chips_per_pod", 1))
            if (count is None) == (shape is None):
                raise ValueError(
                    "probe wants exactly one of ?count= / ?shape=")
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e)) from None
        doc = await asyncio.to_thread(
            lambda: router.capacity_probe(
                count=count, shape=shape, chips_per_pod=cpp))
        if doc is None:
            raise web.HTTPNotFound(
                text="capacity analytics disabled "
                     "(set capacity_enabled)")
        return web.json_response(doc)

    async def lockgraph_route(request: web.Request) -> web.Response:
        doc = await asyncio.to_thread(router.lockgraph_report)
        if doc is None:
            raise web.HTTPNotFound(
                text="dynamic lock-order detector disabled "
                     "(set lock_monitor)")
        return web.json_response(doc)

    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/statusz", statusz)
    app.router.add_get("/lockgraph", lockgraph_route)
    app.router.add_get("/explain", explain)
    app.router.add_get("/events", events)
    app.router.add_get("/trace", trace_route)
    app.router.add_get("/capacity", capacity)
    app.router.add_get("/capacity/probe", capacity_probe)
    return app


def main_worker(argv: Optional[list[str]] = None) -> int:
    """``tpukube.cli shard-worker`` — the per-replica planner daemon
    the SubprocessTransport spawns (and a production replica runs)."""
    import argparse

    from tpukube.core.config import load_config

    p = argparse.ArgumentParser(
        prog="tpukube-shard-worker",
        description="one planner replica of the sharded control plane",
    )
    p.add_argument("--config", metavar="YAML", required=True,
                   help="resolved per-replica config (the router "
                        "writes one; production pins one per replica)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--fake-clock", action="store_true",
                   help="run scheduling-semantic time on a FakeClock "
                        "advanced by the router (/worker/advance) — "
                        "the sim/bench plane's discrete-event mode")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    logging.basicConfig(
        level=(logging.WARNING, logging.INFO,
               logging.DEBUG)[min(args.verbose, 2)],
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    cfg = load_config(yaml_path=args.config)
    if cfg.planner_replicas != 1:
        p.error("a shard worker is ONE planner replica: the config "
                "must say planner_replicas: 1 (the router writes "
                "per-replica configs; see sched/shard.py)")
    from tpukube.core.clock import SYSTEM, FakeClock

    clock = FakeClock() if args.fake_clock else SYSTEM
    # federated lockgraph (ISSUE 18): install the dynamic lock-order
    # detector BEFORE the Extender is built so every scheduling lock
    # this replica creates is wrapped; the observed edge set then rides
    # replica_summary's lock_graph key over /worker/summary and the
    # router merges a fleet-wide cycle report
    monitor_installed = False
    if cfg.lock_monitor:
        from tpukube.analysis import lockgraph

        lockgraph.install()
        monitor_installed = True
    extender = Extender(cfg, clock=clock)
    # SHARD_WORKER_PROFILE=<path>: dump a cProfile of this worker's
    # whole life to <path>.<port> at shutdown — the only way to see
    # where a replica daemon's plan wall goes from the router side.
    # Deliberately NOT a TPUKUBE_* var: the router scrubs those from
    # worker env so the per-replica YAML stays the one config source.
    import os

    prof = None
    prof_path = os.environ.get("SHARD_WORKER_PROFILE")
    if prof_path:
        import cProfile

        prof = cProfile.Profile()
        prof.enable()
    log.warning("shard worker serving on %s:%d (fake_clock=%s)",
                args.host, args.port, args.fake_clock)
    try:
        web.run_app(make_worker_app(extender, clock=clock),
                    host=args.host, port=args.port,
                    print=None, handle_signals=True)
    finally:
        if prof is not None:
            prof.disable()
            prof.dump_stats(f"{prof_path}.{args.port}")
        if extender.trace is not None:
            extender.trace.close()
        if extender.decisions is not None:
            extender.decisions.close()
        if extender.capacity is not None:
            extender.capacity.close()
        extender.events.close()
        if extender.journal is not None:
            extender.journal.close()
            extender.state.retire()
        if monitor_installed:
            from tpukube.analysis import lockgraph

            lockgraph.uninstall()
    return 0

"""Epoch-cached scheduling snapshots (the kube-scheduler analog of the
per-cycle scheduling snapshot + equivalence cache).

Every /filter, /prioritize, and preemption plan used to re-derive
topology state from the ledger: rebuild the occupancy grid, a fresh
summed-area table, and the gang masks — per webhook, per slice. On the
ROADMAP's hardware-speed north star that O(volume x shapes x origins)
per-webhook rebuild was the dominant hot path. This module makes the
derived state a CACHED artifact:

  * :class:`SliceSnapshot` — one ICI slice's scheduling view: the
    occupied / reserved / unhealthy / terminating coord sets, broken
    links, and (lazily) the prepared :class:`~tpukube.sched.slicefit.
    _Sweep` objects (occupancy grid + integral-image table + free-box
    index) plus cached fragmentation / largest-free-box numbers.
  * :class:`ClusterSnapshot` — the per-slice snapshots under one epoch
    key.
  * :class:`SnapshotCache` — epoch-tagged cache owned by the
    GangManager (shared with the Extender): ``current()`` returns the
    cached snapshot while the (ledger epoch, gang epoch) key is
    unchanged and rebuilds lazily — at most once per epoch — otherwise.

Epoch discipline: every ledger mutation (commit / release / node
upsert / rebuild) bumps ``ClusterState.epoch()``; every reservation
mutation (reserve / rollback / dissolve / assignment / terminating-mask
change / eviction confirm) bumps ``GangManager.epoch()``. A snapshot is
valid exactly while both epochs stand still, so a stale-snapshot
placement is structurally impossible — the failure mode the chaos
scenarios must never see.

Locking: ``current()`` reads both epochs (ledger + gang locks) and
builds OUTSIDE the cache's own mutex, which therefore stays a leaf lock
— callers may hold the decision or gang lock (the existing
``decision -> pending -> gang -> ledger`` order), never the reverse.
Webhook cycles take the snapshot once at the top under the decision
lock; metrics/statusz scrapes may race mutations, in which case the
torn build is served once but never cached (the epoch re-check fails).

tpukube-lint's ``snapshot-discipline`` pass enforces the routing: this
module and ``slicefit`` (the primitive definitions and their grid-based
thin wrappers) are the only places allowed to construct
``occupancy_grid``/``_Sweep`` — a call site quietly rebuilding sweeps
per webhook again is a lint finding, so the cache cannot silently rot.

The epoch discipline itself is enforced twice over (ISSUE 7): the
``epoch-discipline`` CFG dataflow pass (``analysis/epochs.py``) proves
statically that every registered mutation seam bumps before its lock
region exits, and the config-gated audit sentinel here
(``snapshot_audit_rate``) rebuilds a sampled fraction of cache hits
from the ledger at runtime, raising :class:`SnapshotAuditError` on any
divergence — so a seam the static registry misses still cannot serve
stale placements silently.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Any, Iterable, Optional

from tpukube.core.mesh import MeshSpec
from tpukube.core.types import Link, TopologyCoord
from tpukube.sched import slicefit

log = logging.getLogger("tpukube.snapshot")


class SnapshotAuditError(RuntimeError):
    """The audit sentinel rebuilt a snapshot from the ledger and it
    diverged from the epoch-cached one: some mutation path changed
    scheduling state WITHOUT bumping an epoch — the stale-cache bug
    class the epoch discipline (static: tpukube-lint epoch-discipline;
    registries in analysis/epochs.py) exists to prevent."""


def sweep_for(
    mesh: MeshSpec, blocked: Iterable[TopologyCoord]
) -> "slicefit._Sweep":
    """Ad-hoc sweep over a REQUEST-SPECIFIC blocked set (a preemption
    plan's victims-look-free grid, a restore's members-look-free grid).
    These grids depend on the request, not just cluster state, so they
    cannot live in the epoch cache — but their construction still
    routes through here so the snapshot-discipline lint keeps all sweep
    building in one auditable place."""
    return slicefit._Sweep(mesh, slicefit.occupancy_grid(mesh, blocked))


class SliceSnapshot:
    """One ICI slice's scheduling state, frozen at an epoch and prepared
    for repeated queries. Coord sets are frozen (callers must not — and
    cannot — mutate them); sweeps, fragmentation, and the largest free
    box build lazily on first use and are then shared by every caller
    of the same snapshot (races on the lazy builds are benign: the
    result is deterministic and assignment is atomic)."""

    __slots__ = (
        "slice_id", "mesh", "occupied", "reserved", "unhealthy",
        "terminating", "broken", "utilization",
        "_occ_sweep", "_blocked_sweep", "_frag", "_largest",
    )

    def __init__(
        self,
        slice_id: str,
        mesh: MeshSpec,
        occupied: frozenset[TopologyCoord],
        reserved: frozenset[TopologyCoord],
        unhealthy: frozenset[TopologyCoord],
        terminating: frozenset[TopologyCoord],
        broken: frozenset[Link],
        utilization: float,
    ):
        self.slice_id = slice_id
        self.mesh = mesh
        #: chips with used shares or bad health (ledger view)
        self.occupied = occupied
        #: gang mask: unassigned reservation chips + terminating victims
        self.reserved = reserved
        self.unhealthy = unhealthy
        #: evicted-but-still-terminating victims' chips (preemption
        #: planners treat these like unhealthy: nothing frees them sooner)
        self.terminating = terminating
        self.broken = broken
        self.utilization = utilization
        self._occ_sweep: Optional[slicefit._Sweep] = None
        self._blocked_sweep: Optional[slicefit._Sweep] = None
        self._frag: Optional[float] = None
        self._largest: Optional[int] = None

    # -- prepared sweeps ---------------------------------------------------
    def occupancy_sweep(self) -> "slicefit._Sweep":
        """Sweep over the OCCUPIED grid (allocated + unhealthy chips) —
        the scorer's fallback and the fragmentation metric's base."""
        sweep = self._occ_sweep
        if sweep is None:
            sweep = self._occ_sweep = sweep_for(self.mesh, self.occupied)
        return sweep

    def blocked_sweep(self) -> "slicefit._Sweep":
        """Sweep over occupied | reserved — what every placement search
        (gang reservation, prioritize scoring) masks against."""
        sweep = self._blocked_sweep
        if sweep is None:
            sweep = self._blocked_sweep = sweep_for(
                self.mesh, self.occupied | self.reserved
            )
        return sweep

    # -- derived numbers ---------------------------------------------------
    @property
    def free_chips(self) -> int:
        """Chips neither occupied nor unhealthy (reservation-blind).
        Pure set arithmetic — counting must not force a sweep build."""
        return self.mesh.num_chips - len(self.occupied)

    @property
    def blocked_free_chips(self) -> int:
        """Chips free for a NEW placement (occupied and reserved both
        masked) — the gang layer's capacity-ranking number. The union
        handles the (normally disjoint) sets overlapping, exactly as
        the OR'd grid the blocked sweep is built from would."""
        return self.mesh.num_chips - len(self.occupied | self.reserved)

    def largest_free_box(self) -> int:
        if self._largest is None:
            self._largest = slicefit.largest_free_box_in(
                self.occupancy_sweep()
            )
        return self._largest

    def fragmentation(self) -> float:
        """Cached ``slicefit.fragmentation`` over the occupied grid."""
        if self._frag is None:
            free = self.free_chips
            self._frag = (
                0.0 if free == 0
                else 1.0 - self.largest_free_box() / free
            )
        return self._frag


class ClusterSnapshot:
    """Per-slice snapshots under one (ledger epoch, gang epoch) key."""

    __slots__ = ("key", "slices", "built_at", "build_seconds")

    def __init__(self, key: tuple[int, int],
                 slices: dict[str, SliceSnapshot],
                 build_seconds: float = 0.0):
        self.key = key
        self.slices = slices
        self.built_at = time.monotonic()
        self.build_seconds = build_seconds

    def slice_ids(self) -> list[str]:
        return sorted(self.slices)

    def slice(self, slice_id: str) -> SliceSnapshot:
        try:
            return self.slices[slice_id]
        except KeyError:
            raise KeyError(
                f"snapshot holds no slice {slice_id!r} "
                f"(has {sorted(self.slices)})"
            ) from None

    def reserved_by_slice(self) -> dict[str, frozenset[TopologyCoord]]:
        """The per-slice gang mask, in the shape the extender's
        feasibility/scoring helpers consume."""
        return {sid: ss.reserved for sid, ss in self.slices.items()}


def _audit_divergence(cached: ClusterSnapshot,
                      rebuilt: ClusterSnapshot) -> list[str]:
    """Human-readable differences between a cached snapshot and a fresh
    ledger rebuild at the same epochs (empty = identical). Compares the
    captured coord/link sets and utilization — the inputs every sweep,
    score, and placement decision derives from; the lazy sweep tables
    are pure functions of these."""
    diffs: list[str] = []
    if set(cached.slices) != set(rebuilt.slices):
        diffs.append(
            f"slice set {sorted(cached.slices)} != "
            f"{sorted(rebuilt.slices)}"
        )
        return diffs
    for sid in sorted(cached.slices):
        a, b = cached.slices[sid], rebuilt.slices[sid]
        for attr in ("occupied", "reserved", "unhealthy", "terminating",
                     "broken"):
            va, vb = getattr(a, attr), getattr(b, attr)
            if va != vb:
                extra = sorted(tuple(x) if not isinstance(x, tuple) else x
                               for x in (va - vb))[:3]
                missing = sorted(tuple(x) if not isinstance(x, tuple)
                                 else x for x in (vb - va))[:3]
                diffs.append(
                    f"{sid}.{attr}: cached has {len(va)}, ledger has "
                    f"{len(vb)} (stale extra {extra}, missing {missing})"
                )
        if abs(a.utilization - b.utilization) > 1e-9:
            diffs.append(
                f"{sid}.utilization: cached {a.utilization:.6f} != "
                f"ledger {b.utilization:.6f}"
            )
        if a.mesh != b.mesh:
            diffs.append(f"{sid}.mesh: cached {a.mesh.dims} != "
                         f"ledger {b.mesh.dims}")
    return diffs


class SnapshotCache:
    """The epoch-tagged snapshot owner. One instance per GangManager
    (the Extender shares it): ``current()`` is safe from any thread and
    from under the decision/gang locks, and rebuilds at most once per
    (ledger, gang) epoch pair."""

    REBUILD_WINDOW = 512  # rebuild-latency samples kept for quantiles

    def __init__(self, state, gang):
        self._state = state
        self._gang = gang
        # leaf mutex: guards only the cached-snapshot slot and the
        # counters — never held while taking the gang/ledger locks
        self._lock = threading.Lock()
        self._snap: Optional[ClusterSnapshot] = None
        self.rebuilds = 0
        self.hits = 0
        self._rebuild_seconds: deque[float] = deque(
            maxlen=self.REBUILD_WINDOW
        )
        # Audit sentinel (config ``snapshot_audit_rate``, wired by the
        # Extender): on a sampled fraction of cache HITS, rebuild the
        # snapshot from the ledger and raise SnapshotAuditError on any
        # divergence — the runtime counterpart of the epoch-discipline
        # static pass, catching mutation seams its registry misses.
        # 0.0 (default) disables the sentinel entirely.
        self.audit_rate = 0.0
        self.audit_checks = 0
        self.audit_divergences = 0
        # deterministic sampling stream: audits are a debugging tool
        # and must not add nondeterminism to seeded chaos runs
        self._audit_rng = random.Random(0xA0D17)

    # -- epoch key ---------------------------------------------------------
    def epoch_key(self) -> tuple[int, int]:
        return (self._state.epoch(), self._gang.epoch())

    def invalidate(self) -> None:
        """Drop the cached snapshot (tests and the no-cache microbench
        baseline; production invalidation is epoch bumps, never this)."""
        with self._lock:
            self._snap = None

    # -- the cache ---------------------------------------------------------
    def current(self) -> ClusterSnapshot:
        """The scheduling snapshot for the current epochs: cached while
        nothing mutated, rebuilt lazily otherwise.

        Torn-build story: every mutation path runs under the extender's
        decision lock, and so does every PLACEMENT lookup — a placement
        cycle's build therefore always passes the epoch re-check below
        (the epochs cannot move under it), which is what makes a
        stale- or torn-snapshot placement structurally impossible.
        Only lock-free OBSERVER reads (metrics/statusz scrapes, which
        should come through :meth:`observe`) can race a mutation; a
        build that fails the re-check is served to that one caller
        uncached — no worse than the pre-snapshot renderers, which
        read the accessors sequentially without a global freeze — and
        the next lookup rebuilds clean."""
        return self._lookup(count_hit=True)

    def observe(self) -> ClusterSnapshot:
        """Cache lookup for observability readers (metrics/statusz).
        Never counts a hit — scrape self-traffic counted as hits would
        mask the 'flat hits counter under webhook load' diagnostic the
        counters exist for. A rebuild it performs is still real work
        (one the next scheduling lookup then inherits) and counts."""
        return self._lookup(count_hit=False)

    def _lookup(self, count_hit: bool) -> ClusterSnapshot:
        key = self.epoch_key()
        with self._lock:
            snap = self._snap
            if snap is not None and snap.key == key:
                if count_hit:
                    self.hits += 1
                hit: Optional[ClusterSnapshot] = snap
            else:
                hit = None
        if hit is not None:
            if count_hit and self.audit_rate > 0.0:
                # audit OUTSIDE the leaf mutex: the rebuild takes the
                # gang/ledger locks, which must never nest inside it.
                # Only counted (scheduling) hits are audited — observer
                # scrapes may race mutations and would false-positive.
                self._maybe_audit(hit)
            return hit
        for _ in range(3):
            t0 = time.perf_counter()
            snap = self._build(key)
            snap.build_seconds = time.perf_counter() - t0
            after = self.epoch_key()
            with self._lock:
                self.rebuilds += 1
                self._rebuild_seconds.append(snap.build_seconds)
                if after == key:
                    self._snap = snap
                    return snap
            key = after
        return snap  # an observer raced mutations: serve uncached

    # -- audit sentinel ----------------------------------------------------
    def _maybe_audit(self, snap: ClusterSnapshot) -> None:
        """Sampled hit audit: rebuild from the ledger and compare.
        Raises :class:`SnapshotAuditError` on divergence — a mutation
        happened without an epoch bump, so the cache was serving stale
        placements. Callers under the decision lock cannot race
        mutations; a lookup that still observes moving epochs (a
        lock-free test caller) is skipped rather than misreported."""
        if (self.audit_rate < 1.0
                and self._audit_rng.random() >= self.audit_rate):
            return
        rebuilt = self._build(snap.key)
        if self.epoch_key() != snap.key:
            return  # raced a mutation: the cached epochs moved mid-audit
        with self._lock:
            self.audit_checks += 1
        diffs = _audit_divergence(snap, rebuilt)
        if diffs:
            with self._lock:
                self.audit_divergences += 1
            detail = "; ".join(diffs[:4])
            log.error("snapshot audit DIVERGENCE at epochs %s: %s",
                      snap.key, detail)
            raise SnapshotAuditError(
                f"cached snapshot at epochs {snap.key} diverges from a "
                f"ledger rebuild ({detail}) — some mutation path is "
                f"missing an epoch bump (see analysis/epochs.py "
                f"EPOCH_REGISTRY and the epoch-discipline lint)"
            )

    def _build(self, key: tuple[int, int]) -> ClusterSnapshot:
        slices: dict[str, SliceSnapshot] = {}
        for sid in self._state.slice_ids():
            try:
                mesh = self._state.slice_mesh(sid)
            except Exception as e:
                # slice vanished mid-build (a racing scrape); the epoch
                # re-check in current() refuses to cache this build
                log.warning("snapshot build: slice %s vanished: %s",
                            sid, e)
                continue
            slices[sid] = SliceSnapshot(
                slice_id=sid,
                mesh=mesh,
                occupied=frozenset(self._state.occupied_coords(sid)),
                reserved=frozenset(self._gang.reserved_coords(sid)),
                unhealthy=frozenset(self._state.unhealthy_coords(sid)),
                terminating=frozenset(self._gang.terminating_coords(sid)),
                broken=frozenset(self._state.broken_links(sid)),
                utilization=self._state.slice_utilization(sid),
            )
        return ClusterSnapshot(key=key, slices=slices)

    # -- observability -----------------------------------------------------
    def rebuild_seconds_snapshot(self) -> list[float]:
        """Copy of the rebuild-latency window (the /metrics summary's
        values_fn — copied under the mutex so a concurrent rebuild can
        never corrupt the scrape)."""
        with self._lock:
            return list(self._rebuild_seconds)

    def stats(self) -> dict[str, Any]:
        """The /statusz document: cache counters plus the per-slice
        fragmentation numbers the snapshot makes cheap to serve.
        Reads via observe() — a statusz poll must not inflate the
        hit counters it reports."""
        snap = self.observe()
        with self._lock:
            rebuilds, hits = self.rebuilds, self.hits
            checks, diverged = self.audit_checks, self.audit_divergences
            last = (self._rebuild_seconds[-1]
                    if self._rebuild_seconds else None)
        lookups = rebuilds + hits
        return {
            "epoch": {"ledger": snap.key[0], "gang": snap.key[1]},
            "rebuilds": rebuilds,
            "hits": hits,
            "audit": {
                "rate": self.audit_rate,
                "checks": checks,
                "divergences": diverged,
            },
            "hit_rate": round(hits / lookups, 4) if lookups else None,
            "last_rebuild_s": (round(last, 6) if last is not None
                               else None),
            "slices": {
                sid: {
                    "fragmentation": round(ss.fragmentation(), 4),
                    "largest_free_box": ss.largest_free_box(),
                    "free_chips": ss.free_chips,
                    "reserved_chips": len(ss.reserved),
                    "links_down": len(ss.broken),
                }
                for sid, ss in snap.slices.items()
            },
        }

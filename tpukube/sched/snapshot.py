"""Epoch-cached scheduling snapshots (the kube-scheduler analog of the
per-cycle scheduling snapshot + equivalence cache).

Every /filter, /prioritize, and preemption plan used to re-derive
topology state from the ledger: rebuild the occupancy grid, a fresh
summed-area table, and the gang masks — per webhook, per slice. On the
ROADMAP's hardware-speed north star that O(volume x shapes x origins)
per-webhook rebuild was the dominant hot path. This module makes the
derived state a CACHED artifact:

  * :class:`SliceSnapshot` — one ICI slice's scheduling view: the
    occupied / reserved / unhealthy / terminating coord sets, broken
    links, and (lazily) the prepared :class:`~tpukube.sched.slicefit.
    _Sweep` objects (occupancy grid + integral-image table + free-box
    index) plus cached fragmentation / largest-free-box numbers.
  * :class:`ClusterSnapshot` — the per-slice snapshots under one epoch
    key.
  * :class:`SnapshotCache` — epoch-tagged cache owned by the
    GangManager (shared with the Extender): ``current()`` returns the
    cached snapshot while the (ledger epoch, gang epoch) key is
    unchanged and rebuilds lazily — at most once per epoch — otherwise.

Epoch discipline: every ledger mutation (commit / release / node
upsert / rebuild) bumps ``ClusterState.epoch()``; every reservation
mutation (reserve / rollback / dissolve / assignment / terminating-mask
change / eviction confirm) bumps ``GangManager.epoch()``. A snapshot is
valid exactly while both epochs stand still, so a stale-snapshot
placement is structurally impossible — the failure mode the chaos
scenarios must never see.

Locking: ``current()`` reads both epochs (ledger + gang locks) and
builds OUTSIDE the cache's own mutex, which therefore stays a leaf lock
— callers may hold the decision or gang lock (the existing
``decision -> pending -> gang -> ledger`` order), never the reverse.
Webhook cycles take the snapshot once at the top under the decision
lock; metrics/statusz scrapes may race mutations, in which case the
torn build is served once but never cached (the epoch re-check fails).

tpukube-lint's ``snapshot-discipline`` pass enforces the routing: this
module and ``slicefit`` (the primitive definitions and their grid-based
thin wrappers) are the only places allowed to construct
``occupancy_grid``/``_Sweep`` — a call site quietly rebuilding sweeps
per webhook again is a lint finding, so the cache cannot silently rot.

The epoch discipline itself is enforced twice over (ISSUE 7): the
``epoch-discipline`` CFG dataflow pass (``analysis/epochs.py``) proves
statically that every registered mutation seam bumps before its lock
region exits, and the config-gated audit sentinel here
(``snapshot_audit_rate``) rebuilds a sampled fraction of cache hits
from the ledger at runtime, raising :class:`SnapshotAuditError` on any
divergence — so a seam the static registry misses still cannot serve
stale placements silently.

Incremental maintenance (ISSUE 10): an epoch bump used to mean a full
O(chips) rebuild — every node view re-scanned to recapture the coord
sets — which at 10k nodes dominates the per-cycle constant the batch
planner left behind. Now every bump seam in ``sched/state.py`` and
``sched/gang.py`` also records a typed :class:`SnapshotDelta` into the
cache's bounded per-stream log (``note()``), and ``current()``
ADVANCES the cached snapshot by applying the queued deltas instead of
rebuilding:

  * ledger deltas carry explicit per-slice occupied-chip add/remove
    sets plus the used-share change (commit/release are the O(Δ) hot
    seams — the 40k-chip occupied set is patched, never re-derived);
  * gang deltas name the touched slices; the (small) reserved /
    terminating masks of exactly those slices are re-read from the
    live GangManager at apply time — set-delta arithmetic over the
    union semantics of ``reserved_coords`` (unassigned reservation
    chips ∪ terminating victims, which may overlap) would be easy to
    get subtly wrong, and re-deriving a few-hundred-coord mask is
    already O(Δ), so the masks use the single existing source of
    truth. The epoch re-check after the advance keeps the torn-build
    contract identical to ``_build``'s (see ``current()``);
  * only the TOUCHED slices get fresh :class:`SliceSnapshot` objects
    (their lazy sweeps / fragmentation gauges invalidate); untouched
    slices are shared by reference and keep their warm sweep tables;
  * structural changes — node upsert with a changed payload, slice
    registration, ``rebuild_from_pods`` — record a ``full`` marker,
    and a marker, a log gap (overflow), or an unknown slice falls back
    to the full rebuild. A bump whose seam forgot to ``note()`` shows
    up as a gap, so a missing delta degrades to a rebuild instead of a
    stale cache.

The audit sentinel cross-checks the delta math at runtime: it compares
the (possibly delta-advanced) cached snapshot against a cold ledger
rebuild, so a wrong delta raises :class:`SnapshotAuditError` exactly
like a missed epoch bump. ``snapshot_delta_enabled=false`` disables
the log and restores the rebuild-every-epoch behavior (the oracle the
parity tests compare against).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Any, Iterable, Optional

from tpukube.core.mesh import MeshSpec
from tpukube.core.types import Link, TopologyCoord
from tpukube.sched import slicefit

log = logging.getLogger("tpukube.snapshot")


class SnapshotAuditError(RuntimeError):
    """The audit sentinel rebuilt a snapshot from the ledger and it
    diverged from the epoch-cached one: some mutation path changed
    scheduling state WITHOUT bumping an epoch — the stale-cache bug
    class the epoch discipline (static: tpukube-lint epoch-discipline;
    registries in analysis/epochs.py) exists to prevent."""


class SnapshotDelta:
    """One epoch bump's snapshot-visible effect, recorded by the seam
    that bumped (under its own lock, so per-stream order is bump
    order). Two streams, keyed by which epoch the bump advanced:

      * ``kind="ledger"`` (ClusterState._epoch): explicit per-slice
        occupied-chip transitions — ``occupied_add`` are chips whose
        used shares left zero (or that a commit claimed whole),
        ``occupied_remove`` chips whose shares returned to zero on a
        healthy chip — plus the used-share change feeding the slice
        utilization. A HEALTH-ONLY node re-annotation (the churn shape
        of health watches: same chips, same links, only per-chip health
        flipped) also travels as a ledger delta — ``unhealthy_add`` /
        ``unhealthy_remove`` plus the healthy-capacity movement in
        ``total_shares_delta`` (and the used/occupied consequences of
        chips entering/leaving health) — O(chips-per-node) instead of
        the full-rebuild marker every changed payload used to cost.
        Any OTHER payload change (links, topology, sharing mode) stays
        a ``full`` marker (below).
      * ``kind="gang"`` (GangManager._epoch): the ``slices`` whose
        reserved / terminating masks changed; the masks themselves are
        re-read from the GangManager at apply time (they are O(Δ)-small
        and their union semantics live in ``reserved_coords``).

    ``full=True`` marks a structural change (node upsert with a changed
    payload, slice registration) that invalidates the whole cached
    snapshot: the advance path refuses the chain and falls back to a
    full rebuild."""

    __slots__ = ("kind", "epoch", "full", "slice_id", "occupied_add",
                 "occupied_remove", "used_shares_delta",
                 "unhealthy_add", "unhealthy_remove",
                 "total_shares_delta", "slices", "why")

    def __init__(self, kind: str, epoch: int, full: bool = False,
                 slice_id: Optional[str] = None,
                 occupied_add: tuple = (), occupied_remove: tuple = (),
                 used_shares_delta: int = 0,
                 unhealthy_add: tuple = (), unhealthy_remove: tuple = (),
                 total_shares_delta: int = 0,
                 slices: tuple = (), why: str = ""):
        assert kind in ("ledger", "gang"), kind
        self.kind = kind
        self.epoch = epoch  # the epoch value AFTER the bump
        self.full = full
        self.slice_id = slice_id
        self.occupied_add = occupied_add
        self.occupied_remove = occupied_remove
        self.used_shares_delta = used_shares_delta
        # health-only re-annotation stream: per-chip health transitions
        # plus the healthy-share capacity they move (total only changes
        # through these; every other topology change is a full marker)
        self.unhealthy_add = unhealthy_add
        self.unhealthy_remove = unhealthy_remove
        self.total_shares_delta = total_shares_delta
        self.slices = slices
        self.why = why

    def __repr__(self) -> str:  # debugging / test failure readability
        return (f"SnapshotDelta({self.kind}@{self.epoch}"
                f"{', FULL' if self.full else ''}"
                f"{f', {self.why}' if self.why else ''})")


def sweep_for(
    mesh: MeshSpec, blocked: Iterable[TopologyCoord]
) -> "slicefit._Sweep":
    """Ad-hoc sweep over a REQUEST-SPECIFIC blocked set (a preemption
    plan's victims-look-free grid, a restore's members-look-free grid).
    These grids depend on the request, not just cluster state, so they
    cannot live in the epoch cache — but their construction still
    routes through here so the snapshot-discipline lint keeps all sweep
    building in one auditable place."""
    return slicefit._Sweep(mesh, slicefit.occupancy_grid(mesh, blocked))


class SliceSnapshot:
    """One ICI slice's scheduling state, frozen at an epoch and prepared
    for repeated queries. Coord sets are frozen (callers must not — and
    cannot — mutate them); sweeps, fragmentation, and the largest free
    box build lazily on first use and are then shared by every caller
    of the same snapshot (races on the lazy builds are benign: the
    result is deterministic and assignment is atomic)."""

    __slots__ = (
        "slice_id", "mesh", "occupied", "reserved", "unhealthy",
        "terminating", "cordoned", "absent", "broken", "used_shares",
        "total_shares",
        "_occ_sweep", "_blocked_sweep", "_frag", "_largest",
    )

    def __init__(
        self,
        slice_id: str,
        mesh: MeshSpec,
        occupied: frozenset[TopologyCoord],
        reserved: frozenset[TopologyCoord],
        unhealthy: frozenset[TopologyCoord],
        terminating: frozenset[TopologyCoord],
        broken: frozenset[Link],
        used_shares: int,
        total_shares: int,
        cordoned: frozenset[TopologyCoord] = frozenset(),
        absent: frozenset[TopologyCoord] = frozenset(),
    ):
        self.slice_id = slice_id
        self.mesh = mesh
        #: chips with used shares or bad health (ledger view)
        self.occupied = occupied
        #: gang mask: unassigned reservation chips + terminating victims
        self.reserved = reserved
        self.unhealthy = unhealthy
        #: evicted-but-still-terminating victims' chips (preemption
        #: planners treat these like unhealthy: nothing frees them sooner)
        self.terminating = terminating
        #: drain mask (fleet elasticity, ISSUE 19): chips of cordoned
        #: nodes — excluded from every NEW placement, while chips they
        #: already serve stay accounted through ``occupied`` as usual.
        #: Cordon transitions travel as full-rebuild markers (rare by
        #: design), so the delta-advance path carries this set through
        #: untouched.
        self.cordoned = cordoned
        #: geometry mask (fleet elasticity, ISSUE 19): chips whose host
        #: left the cluster (un-ingest, spot churn) or never arrived (a
        #: recovery rebuilt from a partially-advertised fleet). Unlike
        #: ``cordoned`` there is nothing live behind these coords at
        #: all — every sweep and capacity count must treat them as
        #: non-existent, or a shrunken slice advertises phantom chips.
        #: Topology changes travel as full-rebuild markers, so the
        #: delta-advance path carries this set through untouched.
        self.absent = absent
        self.broken = broken
        #: allocated / total shares over healthy capacity — carried as
        #: the two INTEGERS (not the derived float) so a ledger delta
        #: can advance utilization in O(1); total only moves on health/
        #: topology changes, which are full-rebuild markers
        self.used_shares = used_shares
        self.total_shares = total_shares
        self._occ_sweep: Optional[slicefit._Sweep] = None
        self._blocked_sweep: Optional[slicefit._Sweep] = None
        self._frag: Optional[float] = None
        self._largest: Optional[int] = None

    @property
    def utilization(self) -> float:
        """Allocated share fraction over healthy capacity (the gang
        layer's bin-pack signal), derived from the carried counts."""
        return self.used_shares / self.total_shares if self.total_shares \
            else 0.0

    # -- prepared sweeps ---------------------------------------------------
    def occupancy_sweep(self) -> "slicefit._Sweep":
        """Sweep over the OCCUPIED grid (allocated + unhealthy + absent
        chips) — the scorer's fallback and the fragmentation metric's
        base. Absent chips block here too: there is no hardware behind
        them to ever free up."""
        sweep = self._occ_sweep
        if sweep is None:
            sweep = self._occ_sweep = sweep_for(
                self.mesh, self.occupied | self.absent)
        return sweep

    def blocked_sweep(self) -> "slicefit._Sweep":
        """Sweep over occupied | reserved | cordoned | absent — what
        every placement search (gang reservation, prioritize scoring)
        masks against. Cordoned chips are drain-blocked: live
        allocations on them keep serving, but nothing NEW lands there.
        Absent chips have no host at all."""
        sweep = self._blocked_sweep
        if sweep is None:
            sweep = self._blocked_sweep = sweep_for(
                self.mesh,
                self.occupied | self.reserved | self.cordoned
                | self.absent
            )
        return sweep

    def uncordoned_sweep(self) -> "slicefit._Sweep":
        """Sweep over occupied | reserved | absent ONLY — the
        drain-pressure counterfactual (obs/capacity.py: would this
        demand fit if the cordoned chips were given back?). Absent
        chips stay masked: cancelling a drain does not resurrect a
        host that already left. Uncached: probed only while a drain is
        in flight."""
        if not self.cordoned:
            return self.blocked_sweep()
        return sweep_for(
            self.mesh, self.occupied | self.reserved | self.absent)

    # -- derived numbers ---------------------------------------------------
    @property
    def free_chips(self) -> int:
        """Chips neither occupied nor unhealthy nor absent
        (reservation-blind). Pure set arithmetic — counting must not
        force a sweep build."""
        return self.mesh.num_chips - len(self.occupied | self.absent)

    @property
    def blocked_free_chips(self) -> int:
        """Chips free for a NEW placement (occupied, reserved,
        cordoned, and absent all masked) — the gang layer's
        capacity-ranking number. The union handles the (normally
        disjoint) sets overlapping, exactly as the OR'd grid the
        blocked sweep is built from would."""
        return self.mesh.num_chips - len(
            self.occupied | self.reserved | self.cordoned | self.absent)

    def largest_free_box(self) -> int:
        if self._largest is None:
            self._largest = slicefit.largest_free_box_in(
                self.occupancy_sweep()
            )
        return self._largest

    def fragmentation(self) -> float:
        """Cached ``slicefit.fragmentation`` over the occupied grid."""
        if self._frag is None:
            free = self.free_chips
            self._frag = (
                0.0 if free == 0
                else 1.0 - self.largest_free_box() / free
            )
        return self._frag


class ClusterSnapshot:
    """Per-slice snapshots under one (ledger epoch, gang epoch) key."""

    __slots__ = ("key", "slices", "built_at", "build_seconds")

    def __init__(self, key: tuple[int, int],
                 slices: dict[str, SliceSnapshot],
                 build_seconds: float = 0.0):
        self.key = key
        self.slices = slices
        self.built_at = time.monotonic()
        self.build_seconds = build_seconds

    def slice_ids(self) -> list[str]:
        return sorted(self.slices)

    def slice(self, slice_id: str) -> SliceSnapshot:
        try:
            return self.slices[slice_id]
        except KeyError:
            raise KeyError(
                f"snapshot holds no slice {slice_id!r} "
                f"(has {sorted(self.slices)})"
            ) from None

    def reserved_by_slice(self) -> dict[str, frozenset[TopologyCoord]]:
        """The per-slice gang mask, in the shape the extender's
        feasibility/scoring helpers consume."""
        return {sid: ss.reserved for sid, ss in self.slices.items()}


def _audit_divergence(cached: ClusterSnapshot,
                      rebuilt: ClusterSnapshot) -> list[str]:
    """Human-readable differences between a cached snapshot and a fresh
    ledger rebuild at the same epochs (empty = identical). Compares the
    captured coord/link sets and utilization — the inputs every sweep,
    score, and placement decision derives from; the lazy sweep tables
    are pure functions of these."""
    diffs: list[str] = []
    if set(cached.slices) != set(rebuilt.slices):
        diffs.append(
            f"slice set {sorted(cached.slices)} != "
            f"{sorted(rebuilt.slices)}"
        )
        return diffs
    for sid in sorted(cached.slices):
        a, b = cached.slices[sid], rebuilt.slices[sid]
        for attr in ("occupied", "reserved", "unhealthy", "terminating",
                     "cordoned", "absent", "broken"):
            va, vb = getattr(a, attr), getattr(b, attr)
            if va != vb:
                extra = sorted(tuple(x) if not isinstance(x, tuple) else x
                               for x in (va - vb))[:3]
                missing = sorted(tuple(x) if not isinstance(x, tuple)
                                 else x for x in (vb - va))[:3]
                diffs.append(
                    f"{sid}.{attr}: cached has {len(va)}, ledger has "
                    f"{len(vb)} (stale extra {extra}, missing {missing})"
                )
        if abs(a.utilization - b.utilization) > 1e-9:
            diffs.append(
                f"{sid}.utilization: cached {a.utilization:.6f} != "
                f"ledger {b.utilization:.6f}"
            )
        if a.mesh != b.mesh:
            diffs.append(f"{sid}.mesh: cached {a.mesh.dims} != "
                         f"ledger {b.mesh.dims}")
    return diffs


class SnapshotCache:
    """The epoch-tagged snapshot owner. One instance per GangManager
    (the Extender shares it): ``current()`` is safe from any thread and
    from under the decision/gang locks, and rebuilds at most once per
    (ledger, gang) epoch pair."""

    REBUILD_WINDOW = 512  # rebuild-latency samples kept for quantiles
    #: per-stream delta-log bound: must exceed the deepest epoch run
    #: between two cache lookups (a full batch cycle of assumed
    #: commits plus a completion wave of releases) or the advance
    #: degrades to a full rebuild (overflow). Entries are a few dozen
    #: bytes, so the bound is memory-cheap headroom.
    DELTA_LOG = 16384

    def __init__(self, state, gang):
        self._state = state
        self._gang = gang
        # leaf mutex: guards only the cached-snapshot slot, the delta
        # log, and the counters — never held while taking the
        # gang/ledger locks
        self._lock = threading.Lock()
        self._snap: Optional[ClusterSnapshot] = None
        #: cached-slot generation: bumped on EVERY write of _snap (the
        #: epoch-discipline CFG pass proves the pairing statically —
        #: EPOCH_REGISTRY's sched/snapshot.py entry)
        self._snap_gen = 0
        self.rebuilds = 0
        self.hits = 0
        # Incremental maintenance (ISSUE 10): bump seams note() typed
        # SnapshotDeltas here; current() advances the cached snapshot
        # by applying them instead of rebuilding O(chips). Per-stream
        # deques — appends are ordered by the owning ledger/gang lock.
        self.delta_enabled = True
        self._delta_log: dict[str, deque[SnapshotDelta]] = {
            "ledger": deque(maxlen=self.DELTA_LOG),
            "gang": deque(maxlen=self.DELTA_LOG),
        }
        self.delta_applies = 0
        self.delta_overflows = 0
        self._delta_apply_seconds: deque[float] = deque(
            maxlen=self.REBUILD_WINDOW
        )
        self.delta_apply_seconds_total = 0.0
        self.rebuild_seconds_total = 0.0
        self._rebuild_seconds: deque[float] = deque(
            maxlen=self.REBUILD_WINDOW
        )
        # Audit sentinel (config ``snapshot_audit_rate``, wired by the
        # Extender): on a sampled fraction of cache HITS, rebuild the
        # snapshot from the ledger and raise SnapshotAuditError on any
        # divergence — the runtime counterpart of the epoch-discipline
        # static pass, catching mutation seams its registry misses.
        # 0.0 (default) disables the sentinel entirely.
        self.audit_rate = 0.0
        self.audit_checks = 0
        self.audit_divergences = 0
        # deterministic sampling stream: audits are a debugging tool
        # and must not add nondeterminism to seeded chaos runs
        self._audit_rng = random.Random(0xA0D17)

    # -- epoch key ---------------------------------------------------------
    def epoch_key(self) -> tuple[int, int]:
        return (self._state.epoch(), self._gang.epoch())

    def invalidate(self) -> None:
        """Drop the cached snapshot (tests and the no-cache microbench
        baseline; production invalidation is epoch bumps, never this).
        With no base snapshot the next lookup is a full rebuild — the
        delta log cannot advance from nothing."""
        with self._lock:
            self._snap = None
            self._snap_gen += 1

    def peek(self) -> Optional[ClusterSnapshot]:
        """The cached snapshot IF it is current, else None — never
        builds (checkpoint captures read through here: a capture must
        not force an O(chips) rebuild just to decide whether a seedable
        snapshot exists)."""
        key = self.epoch_key()
        with self._lock:
            snap = self._snap
            return snap if snap is not None and snap.key == key else None

    def seed(self, snap: ClusterSnapshot) -> None:
        """Install a checkpoint-restored snapshot as the cached slot
        (journal recovery's warm path): the first lookups after a
        restart HIT instead of forcing the O(chips) rebuild that would
        eagerly materialize every lazily-restored node view. The caller
        guarantees ``snap.key`` equals the current epoch key and that
        the content matches the restored ledger — the audit sentinel
        (``audit_now`` at recovery with ``snapshot_audit_rate`` > 0,
        plus the sampled runtime audits) holds it to that."""
        with self._lock:
            self._snap = snap
            self._snap_gen += 1

    # -- the delta log -------------------------------------------------------
    def note(self, delta: SnapshotDelta) -> None:
        """Record one bump's effect. Called by the seam that bumped,
        under ITS lock (ledger or gang), so each stream's append order
        is epoch order; the cache mutex stays a leaf. No-op with the
        feature off — every epoch advance then rebuilds, the oracle
        behavior the parity tests compare against."""
        if not self.delta_enabled:
            return
        with self._lock:
            self._delta_log[delta.kind].append(delta)

    def deltas_between(
        self, old_key: tuple[int, int], new_key: tuple[int, int]
    ) -> Optional[list[SnapshotDelta]]:
        """The contiguous delta chain advancing ``old_key`` to
        ``new_key`` (per-stream epoch order; ledger first), or None
        when the log cannot cover the range — entries dropped by the
        bound, a bump whose seam never noted, or the feature off. The
        chain may contain ``full`` markers; callers must treat any
        marker as rebuild-required. Also the batch planner's feed: the
        cycle patches its persistent fast-state overlay from the same
        chain the snapshot advanced by."""
        (s0, g0), (s1, g1) = old_key, new_key
        if s1 < s0 or g1 < g0:
            return None
        out: list[SnapshotDelta] = []
        with self._lock:
            for kind, lo, hi in (("ledger", s0, s1), ("gang", g0, g1)):
                if hi == lo:
                    continue
                # per-stream epochs append in strictly increasing order,
                # so the wanted chain is a SUFFIX (minus entries newer
                # than hi): walk from the right and stop at lo — O(Δ +
                # newer-than-hi), never a full scan of the bounded log
                # (this runs under the leaf mutex that note() also
                # takes from inside the ledger/gang locks, so a full
                # 16k-entry filter here would stall commits)
                got = []
                for d in reversed(self._delta_log[kind]):
                    if d.epoch > hi:
                        continue
                    if d.epoch <= lo:
                        break
                    got.append(d)
                if len(got) != hi - lo:
                    return None  # gap: dropped or never noted
                got.reverse()
                out.extend(got)
        return out

    def _advance(self, base: ClusterSnapshot,
                 key: tuple[int, int]) -> Optional[ClusterSnapshot]:
        """Apply the queued deltas to ``base``, producing the snapshot
        for ``key`` in O(Δ): only touched slices get fresh
        SliceSnapshots (their lazy sweeps invalidate); untouched slices
        are shared by reference. None = not coverable (gap/full/unknown
        slice) — the caller falls back to a full rebuild. Runs OUTSIDE
        the cache mutex; the gang-mask re-reads take the gang lock,
        and may observe state newer than ``key`` under a lock-free
        observer race — the caller's epoch re-check then refuses to
        cache the result, exactly the ``_build`` torn-build contract."""
        deltas = self.deltas_between(base.key, key)
        if deltas is None:
            with self._lock:
                self.delta_overflows += 1
            return None
        if any(d.full for d in deltas):
            return None  # structural change: rebuild is the only truth
        # merge the ledger stream per slice (net add/remove against the
        # base set: an add cancels a pending remove and vice versa)
        occ_add: dict[str, set] = {}
        occ_rem: dict[str, set] = {}
        unh_add: dict[str, set] = {}
        unh_rem: dict[str, set] = {}
        used: dict[str, int] = {}
        total: dict[str, int] = {}
        gang_touched: set[str] = set()

        def _merge(add: set, rem: set, adds, rems) -> None:
            for c in adds:
                rem.discard(c)
                add.add(c)
            for c in rems:
                add.discard(c)
                rem.add(c)

        for d in deltas:
            if d.kind == "gang":
                gang_touched.update(d.slices)
                continue
            sid = d.slice_id
            if sid is None:
                continue  # an empty ledger bump (release on a gone node)
            _merge(occ_add.setdefault(sid, set()),
                   occ_rem.setdefault(sid, set()),
                   d.occupied_add, d.occupied_remove)
            _merge(unh_add.setdefault(sid, set()),
                   unh_rem.setdefault(sid, set()),
                   d.unhealthy_add, d.unhealthy_remove)
            used[sid] = used.get(sid, 0) + d.used_shares_delta
            total[sid] = total.get(sid, 0) + d.total_shares_delta
        touched = set(occ_add) | set(occ_rem) | set(used) | gang_touched
        if not touched <= set(base.slices):
            return None  # slice appeared without a full marker?!
        slices = dict(base.slices)
        for sid in touched:
            old = base.slices[sid]
            occupied = old.occupied
            if occ_add.get(sid) or occ_rem.get(sid):
                occupied = frozenset(
                    (occupied - occ_rem[sid]) | occ_add[sid]
                )
            unhealthy = old.unhealthy
            if unh_add.get(sid) or unh_rem.get(sid):
                # health-only re-annotation deltas (see SnapshotDelta)
                unhealthy = frozenset(
                    (unhealthy - unh_rem[sid]) | unh_add[sid]
                )
            if sid in gang_touched:
                reserved = frozenset(self._gang.reserved_coords(sid))
                terminating = frozenset(
                    self._gang.terminating_coords(sid))
            else:
                reserved, terminating = old.reserved, old.terminating
            slices[sid] = SliceSnapshot(
                slice_id=sid,
                mesh=old.mesh,
                occupied=occupied,
                reserved=reserved,
                unhealthy=unhealthy,
                terminating=terminating,
                broken=old.broken,
                used_shares=old.used_shares + used.get(sid, 0),
                total_shares=old.total_shares + total.get(sid, 0),
                # cordon and topology transitions are full markers
                # (set_cordon, ingest, un-ingest), so the carried sets
                # are exact across any delta chain
                cordoned=old.cordoned,
                absent=old.absent,
            )
        return ClusterSnapshot(key=key, slices=slices)

    # -- the cache ---------------------------------------------------------
    def current(self) -> ClusterSnapshot:
        """The scheduling snapshot for the current epochs: cached while
        nothing mutated, rebuilt lazily otherwise.

        Torn-build story: every mutation path runs under the extender's
        decision lock, and so does every PLACEMENT lookup — a placement
        cycle's build therefore always passes the epoch re-check below
        (the epochs cannot move under it), which is what makes a
        stale- or torn-snapshot placement structurally impossible.
        Only lock-free OBSERVER reads (metrics/statusz scrapes, which
        should come through :meth:`observe`) can race a mutation; a
        build that fails the re-check is served to that one caller
        uncached — no worse than the pre-snapshot renderers, which
        read the accessors sequentially without a global freeze — and
        the next lookup rebuilds clean."""
        return self._lookup(count_hit=True)

    def observe(self) -> ClusterSnapshot:
        """Cache lookup for observability readers (metrics/statusz).
        Never counts a hit — scrape self-traffic counted as hits would
        mask the 'flat hits counter under webhook load' diagnostic the
        counters exist for. A rebuild it performs is still real work
        (one the next scheduling lookup then inherits) and counts."""
        return self._lookup(count_hit=False)

    def _lookup(self, count_hit: bool) -> ClusterSnapshot:
        key = self.epoch_key()
        with self._lock:
            snap = self._snap
            if snap is not None and snap.key == key:
                if count_hit:
                    self.hits += 1
                hit: Optional[ClusterSnapshot] = snap
            else:
                hit = None
            base = snap  # delta-advance base (None = cold start)
        if hit is not None:
            if count_hit and self.audit_rate > 0.0:
                # audit OUTSIDE the leaf mutex: the rebuild takes the
                # gang/ledger locks, which must never nest inside it.
                # Only counted (scheduling) hits are audited — observer
                # scrapes may race mutations and would false-positive.
                self._maybe_audit(hit)
            return hit
        for _ in range(3):
            snap = None
            if (base is not None and self.delta_enabled
                    and base.key != key):
                t0 = time.perf_counter()
                snap = self._advance(base, key)
                if snap is not None:
                    dt = time.perf_counter() - t0
                    with self._lock:
                        self.delta_applies += 1
                        self._delta_apply_seconds.append(dt)
                        self.delta_apply_seconds_total += dt
            if snap is None:
                t0 = time.perf_counter()
                snap = self._build(key)
                snap.build_seconds = time.perf_counter() - t0
                with self._lock:
                    self.rebuilds += 1
                    self._rebuild_seconds.append(snap.build_seconds)
                    self.rebuild_seconds_total += snap.build_seconds
            after = self.epoch_key()
            with self._lock:
                if after == key:
                    self._snap = snap
                    self._snap_gen += 1
                    return snap
            key = after
            base = snap  # labeled for the missed key; advance from it
        return snap  # an observer raced mutations: serve uncached

    # -- audit sentinel ----------------------------------------------------
    def audit_now(self) -> None:
        """One FORCED sentinel check regardless of ``audit_rate`` — the
        journal recovery's recovered-state proof (sched/journal.py):
        the freshly restored-and-reconciled snapshot must equal a
        from-scratch ledger rebuild. Callers run before serving (no
        concurrent mutations), so a moved epoch mid-check is a real
        divergence, not a race. Raises :class:`SnapshotAuditError`."""
        snap = self.current()
        rebuilt = self._build(snap.key, audit=True)
        with self._lock:
            self.audit_checks += 1
        diffs = _audit_divergence(snap, rebuilt)
        if diffs:
            with self._lock:
                self.audit_divergences += 1
            detail = "; ".join(diffs[:4])
            log.error("snapshot audit DIVERGENCE (forced) at epochs "
                      "%s: %s", snap.key, detail)
            raise SnapshotAuditError(
                f"recovered snapshot at epochs {snap.key} diverges "
                f"from a ledger rebuild ({detail})"
            )

    def _maybe_audit(self, snap: ClusterSnapshot) -> None:
        """Sampled hit audit: rebuild from the ledger and compare.
        Raises :class:`SnapshotAuditError` on divergence — a mutation
        happened without an epoch bump, so the cache was serving stale
        placements. Callers under the decision lock cannot race
        mutations; a lookup that still observes moving epochs (a
        lock-free test caller) is skipped rather than misreported."""
        if (self.audit_rate < 1.0
                and self._audit_rng.random() >= self.audit_rate):
            return
        rebuilt = self._build(snap.key, audit=True)
        if self.epoch_key() != snap.key:
            return  # raced a mutation: the cached epochs moved mid-audit
        with self._lock:
            self.audit_checks += 1
        diffs = _audit_divergence(snap, rebuilt)
        if diffs:
            with self._lock:
                self.audit_divergences += 1
            detail = "; ".join(diffs[:4])
            log.error("snapshot audit DIVERGENCE at epochs %s: %s",
                      snap.key, detail)
            raise SnapshotAuditError(
                f"cached snapshot at epochs {snap.key} diverges from a "
                f"ledger rebuild ({detail}) — some mutation path is "
                f"missing an epoch bump, or a recorded SnapshotDelta "
                f"mis-stated its seam's effect (see analysis/epochs.py "
                f"EPOCH_REGISTRY and the epoch-discipline lint)"
            )

    def _build(self, key: tuple[int, int],
               audit: bool = False) -> ClusterSnapshot:
        slices: dict[str, SliceSnapshot] = {}
        for sid in self._state.slice_ids():
            try:
                mesh = self._state.slice_mesh(sid)
            except Exception as e:
                # slice vanished mid-build (a racing scrape); the epoch
                # re-check in current() refuses to cache this build
                log.warning("snapshot build: slice %s vanished: %s",
                            sid, e)
                continue
            # audit builds bypass EVERY incremental ledger cache (the
            # walk_* variants re-derive from the node views): the
            # sentinel exists to catch seams that forgot their
            # bookkeeping, so it must never read a set or counter the
            # same seams maintain
            if audit:
                used, total = self._state.walk_slice_share_counts(sid)
                occupied = self._state.walk_occupied_coords(sid)
                unhealthy = self._state.walk_unhealthy_coords(sid)
                broken = self._state.walk_broken_links(sid)
            else:
                used, total = self._state.slice_share_counts(sid)
                occupied = self._state.occupied_coords(sid)
                unhealthy = self._state.unhealthy_coords(sid)
                broken = self._state.broken_links(sid)
            slices[sid] = SliceSnapshot(
                slice_id=sid,
                mesh=mesh,
                occupied=frozenset(occupied),
                reserved=frozenset(self._gang.reserved_coords(sid)),
                unhealthy=frozenset(unhealthy),
                terminating=frozenset(self._gang.terminating_coords(sid)),
                broken=frozenset(broken),
                used_shares=used,
                total_shares=total,
                # no incremental cache to bypass: cordoned_coords and
                # absent_coords ARE the single derivations (audit and
                # build share them)
                cordoned=frozenset(self._state.cordoned_coords(sid)),
                absent=frozenset(self._state.absent_coords(sid)),
            )
        return ClusterSnapshot(key=key, slices=slices)

    # -- observability -----------------------------------------------------
    def rebuild_seconds_snapshot(self) -> list[float]:
        """Copy of the rebuild-latency window (the /metrics summary's
        values_fn — copied under the mutex so a concurrent rebuild can
        never corrupt the scrape)."""
        with self._lock:
            return list(self._rebuild_seconds)

    def delta_apply_seconds_snapshot(self) -> list[float]:
        """Copy of the delta-apply latency window (the /metrics
        summary's values_fn; one sample per O(Δ) advance, however many
        queued deltas it covered)."""
        with self._lock:
            return list(self._delta_apply_seconds)

    def stats(self) -> dict[str, Any]:
        """The /statusz document: cache counters plus the per-slice
        fragmentation numbers the snapshot makes cheap to serve.
        Reads via observe() — a statusz poll must not inflate the
        hit counters it reports."""
        snap = self.observe()
        with self._lock:
            rebuilds, hits = self.rebuilds, self.hits
            applies, overflows = self.delta_applies, self.delta_overflows
            checks, diverged = self.audit_checks, self.audit_divergences
            last = (self._rebuild_seconds[-1]
                    if self._rebuild_seconds else None)
        lookups = rebuilds + hits
        advances = rebuilds + applies
        return {
            "epoch": {"ledger": snap.key[0], "gang": snap.key[1]},
            "generation": self._snap_gen,
            "rebuilds": rebuilds,
            "hits": hits,
            "audit": {
                "rate": self.audit_rate,
                "checks": checks,
                "divergences": diverged,
            },
            "delta": {
                "enabled": self.delta_enabled,
                "applies": applies,
                "overflows": overflows,
            },
            # of the lookups that had to move the snapshot forward, the
            # fraction the O(Δ) delta path served (vs full rebuilds) —
            # a low rate with the feature on means overflow/structural
            # churn is defeating the increment
            "delta_hit_rate": (round(applies / advances, 4)
                               if advances else None),
            "hit_rate": round(hits / lookups, 4) if lookups else None,
            "last_rebuild_s": (round(last, 6) if last is not None
                               else None),
            "slices": {
                sid: {
                    "fragmentation": round(ss.fragmentation(), 4),
                    "largest_free_box": ss.largest_free_box(),
                    "free_chips": ss.free_chips,
                    "reserved_chips": len(ss.reserved),
                    "links_down": len(ss.broken),
                }
                for sid, ss in snap.slices.items()
            },
        }

"""Fleet autoscaler loop (ISSUE 19): grow/shrink the fleet against
queue depth and tenant SLO burn.

Scale-UP watches the batch queue (``SchedulingCycle.queue_depth``) and
the tenancy plane's SLO-burn verdict (``BurnMonitor.last_page_burning``
— read-only; the admission path slides the windows): sustained depth
at or above ``autoscale_up_queue_depth``, or a burning page, provisions
one new slice through the **bulk-ingest** fast path (one recorded
``upsert_nodes`` decision, one epoch/delta/journal seam). The
provisioner itself is injected (``set_provisioner``) — the sim harness
mints node items; a cloud deployment would call its instance API. No
provisioner means scale-up silently skips (the loop still shrinks).

Scale-DOWN watches utilization: when the fleet idles below
``autoscale_down_utilization`` with an empty queue, the EMPTIEST slice
drains through the DrainCoordinator's graceful choreography (cordon →
budgeted migrate-or-preempt → un-ingest) — which is why
``autoscale_enabled`` requires ``drain_enabled``. Slice-count bounds
(``autoscale_min_slices`` / ``autoscale_max_slices``) and a cooldown
(``autoscale_cooldown_seconds``, scheduling clock — FakeClock
compressible) keep the loop from flapping.

Ticks are amortized onto the decision path like the drain's
(``Extender.handle`` calls ``maybe_tick`` under the decision lock);
the sim drives ``tick()`` directly. Nothing is constructed with the
flag off; no ``tpukube_autoscaler_*`` series render.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

log = logging.getLogger("tpukube.autoscale")


class Autoscaler:
    """One per extender. ``self._lock`` is a LEAF for counters; fleet
    mutations run under the extender's decision lock (``tick`` takes
    it; ``maybe_tick`` is called while it is held — RLock)."""

    def __init__(self, extender, config) -> None:
        self.ext = extender
        self._config = config
        self._lock = threading.Lock()
        #: provisioner: () -> list of {"name", "annotations"} node
        #: items forming ONE new slice (injected by the harness/cloud)
        self._provision: Optional[Callable[[], list]] = None
        self._last_action = -float("inf")
        # scale-up ingests through handle("upsert_nodes"), whose tail
        # calls maybe_tick again — guard against re-entering the
        # evaluation mid-action (flips only under the decision lock)
        self._ticking = False
        # counters (tpukube_autoscaler_* series; rendered only when on)
        self.scale_ups = 0
        self.scale_downs = 0
        self.nodes_added_total = 0
        self.ticks = 0
        self.last_decision = "idle"

    def set_provisioner(self, fn: Callable[[], list]) -> None:
        self._provision = fn

    # -- the loop ----------------------------------------------------------
    def maybe_tick(self) -> None:
        """Amortized driver (caller holds the decision lock): a clock
        read per decision; the real evaluation runs at cooldown
        cadence."""
        if self._ticking:
            return
        now = self.ext.clock.monotonic()
        if now - self._last_action < self._config.autoscale_cooldown_seconds:
            return
        self.tick()

    def tick(self) -> str:
        """One scaling evaluation; returns the decision taken
        ("up" / "down" / "idle"). The cooldown stamps only on action,
        so a quiet fleet re-evaluates freely and a scaling one
        settles between moves."""
        ext = self.ext
        cfg = self._config
        with ext._decision_lock:
            if self._ticking:
                return "idle"
            self._ticking = True
            try:
                return self._tick_locked()
            finally:
                self._ticking = False

    def _tick_locked(self) -> str:
        ext = self.ext
        cfg = self._config
        with self._lock:
            self.ticks += 1
        depth = (ext.cycle.queue_depth()
                 if ext.cycle is not None else 0)
        burning = (ext.tenants is not None
                   and ext.tenants.burn.last_page_burning())
        n_slices = len(ext.state.slice_ids())
        decision = "idle"
        if ((depth >= cfg.autoscale_up_queue_depth or burning)
                and n_slices < cfg.autoscale_max_slices):
            if self._scale_up(depth, burning):
                decision = "up"
        elif (depth == 0
              and ext.state.utilization()
              < cfg.autoscale_down_utilization
              and n_slices > cfg.autoscale_min_slices
              and ext.drain is not None
              and not ext.drain.active()):
            if self._scale_down():
                decision = "down"
        if decision != "idle":
            self._last_action = ext.clock.monotonic()
        with self._lock:
            self.last_decision = decision
        return decision

    def _scale_up(self, depth: int, burning: bool) -> bool:
        """Provision one slice and bulk-ingest it (one recorded
        decision — time-to-capacity is one seam, not O(nodes))."""
        if self._provision is None:
            return False
        try:
            items = list(self._provision())
        except Exception:
            log.exception("autoscaler provisioner failed")
            return False
        if not items:
            return False
        results = self.ext.handle("upsert_nodes", {"items": items})[
            "results"]
        errors = sum(1 for r in results
                     if isinstance(r, dict) and r.get("error"))
        with self._lock:
            self.scale_ups += 1
            self.nodes_added_total += len(items) - errors
        self.ext._emit_event(
            "AutoscaleUp", "autoscaler",
            f"provisioned {len(items)} node(s) ({errors} error(s)): "
            f"queue depth {depth}, slo burning: {bool(burning)}",
            warning=False,
        )
        log.warning("autoscaler: scale-up of %d node(s) "
                    "(depth %d, burning %s)", len(items), depth, burning)
        return True

    def _scale_down(self) -> bool:
        """Drain the emptiest slice through the graceful choreography
        (the drain owns eviction budgets and the final un-ingest)."""
        ext = self.ext
        snap = ext.snapshots.current()
        sids = snap.slice_ids()
        if len(sids) <= self._config.autoscale_min_slices:
            return False
        target = min(sids, key=lambda s: (snap.slice(s).utilization, s))
        nodes = [n for n in ext.state.node_names()
                 if ext.state.slice_of_node(n) == target]
        if not nodes:
            return False
        drain_id = ext.drain.begin(nodes, reason="autoscale-down")
        with self._lock:
            self.scale_downs += 1
        self.ext._emit_event(
            "AutoscaleDown", "autoscaler",
            f"draining slice {target} ({len(nodes)} node(s)) as "
            f"{drain_id}",
            warning=False,
        )
        log.warning("autoscaler: scale-down drains slice %s "
                    "(%d nodes, %s)", target, len(nodes), drain_id)
        return True

    # -- inspection --------------------------------------------------------
    def statusz(self) -> dict[str, Any]:
        with self._lock:
            return {
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "nodes_added_total": self.nodes_added_total,
                "ticks": self.ticks,
                "last_decision": self.last_decision,
                "provisioner": self._provision is not None,
            }

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "nodes_added": self.nodes_added_total,
                "ticks": self.ticks,
            }

"""Compact binary wire codec for the sharded driver surface (ISSUE 20).

At scenario-14 scale (~102k nodes / ~410k chips behind 4 subprocess
replicas) the fanned JSON-over-HTTP `/worker/*` surface dominates
router<->worker cost — PR 16's wire accounting
(`tpukube_router_wire_bytes_total`, per-drive ``bytes_per_wave``, the
flight recorder) measured the bill; this module pays it.  The KubeGPU
lineage (PAPER.md §1) shipped its whole device topology through verbose
annotation JSON; this reproduction keeps JSON as the *parity oracle*
(`wire_codec: json`, the default, leaves every wire body and all
exposition byte-identical) and adds an opt-in compact binary format.

Frame layout (versioned — the magic pins format v1, including the
preset key table below):

    b"TKW1" | flags:1 byte | payload

    flags 0 = raw payload, 1 = zlib-compressed, 2 = zstd-compressed
    (zstd only where the stdlib ships it; the decoder accepts either
    whenever available, the encoder prefers zstd when present).

Payload value encoding is a tag byte followed by tag-specific data.
Three properties make it compact on the hot bodies:

* **Per-op key tables**: the hot bodies (`upsert_nodes` fleet batches,
  `admit_many` pod lists, `planned_many`/`bind_many`/`release_many`
  waves, `allocs_since` reads) are lists of dicts with identical keys
  per item.  A homogeneous dict list is encoded as TAG_TABLE: the key
  tuple once (schema), then bare rows — no per-row key bytes at all.
* **String interning**: every string ≤ _INTERN_MAX bytes is assigned an
  id on first sight (TAG_STR_NEW) and referenced by varint id after
  (TAG_STR_REF).  Node names, slice ids and device ids repeat across
  rows; they serialize once.  The intern rule is symmetric, so the
  decoder rebuilds the table without it being transmitted.
* **Preset key table**: well-known `/worker/*` body keys are pre-seeded
  into the intern table (same list both sides, pinned to the TKW1
  version), so even schema rows for common ops cost one varint per key.

Integers use zigzag varints; floats that survive exact round-trip
through int stay ints only if they *are* ints (floats are always 8-byte
doubles — `decode(encode(x)) == x` is a hard contract, enforced by the
round-trip property tests and the N=1/codec-off placement parity
acceptance).

Content negotiation lives in the transport/worker (sched/shard.py,
sched/shardworker.py): requests and responses carry
``Content-Type: application/x-tpukube-wire`` when binary, and a binary
router facing a JSON-only worker degrades per replica to JSON — the
rolling-upgrade story in deploy/README.md.
"""
from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

try:  # stdlib zstd (Python 3.14+); this container's 3.10 has zlib only
    from compression import zstd as _zstd  # type: ignore
except ImportError:  # pragma: no cover - environment-dependent
    _zstd = None

# HTTP content type announcing/carrying a TKW1 frame. The transport
# sends it in Accept (capability probe) and Content-Type (body format);
# the worker mirrors it back only when the request asked for it.
WIRE_CONTENT_TYPE = "application/x-tpukube-wire"
JSON_CONTENT_TYPE = "application/json"

# Compact separators — the codec-off satellite: journal.py already
# writes compact JSON; the wire should too.
JSON_SEPARATORS = (",", ":")

_MAGIC = b"TKW1"
_FLAG_RAW = 0
_FLAG_ZLIB = 1
_FLAG_ZSTD = 2

# Value tags.
_T_NONE = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT = 3  # zigzag varint
_T_FLOAT = 4  # 8-byte little-endian double
_T_STR_NEW = 5  # varint len + utf-8 bytes; interned if len <= _INTERN_MAX
_T_STR_REF = 6  # varint intern id
_T_STR_BIG = 7  # varint len + utf-8 bytes; never interned
_T_LIST = 8  # varint count + values
_T_DICT = 9  # varint count + (key value)*
_T_TABLE = 10  # varint ncols + keys, varint nrows + bare rows

# Strings longer than this are not interned: the table would grow on
# one-shot payload blobs without ever earning a reference back.
_INTERN_MAX = 64

# Keys pre-seeded into the intern table on BOTH sides, pinned to the
# TKW1 magic (changing this list means bumping the version). These are
# the recurring `/worker/*` body/response keys, so the schema row of a
# TAG_TABLE costs one varint per key even on the first frame.
_PRESET_STRINGS: Tuple[str, ...] = (
    # fleet node batches (upsert_nodes) / node annotations
    "name", "nodes", "node", "slice", "slice_id", "topology", "chips",
    "devices", "device_ids", "badLinks", "bad_links", "labels", "free",
    "used", "capacity", "health", "healthy", "epoch", "generation",
    # pod admission / planning waves
    "pod", "pods", "pod_name", "namespace", "uid", "request", "requests",
    "shape", "count", "priority", "tenant", "gang", "gang_id", "phase",
    "status", "reason", "ok", "error",
    # allocation deltas / rendezvous
    "alloc", "allocs", "allocations", "seq", "since", "deltas", "kind",
    "bind", "binds", "release", "released", "planned", "txn", "txn_id",
    "commit", "abort", "ts",
    # summaries / gauges
    "summary", "gauges", "total", "value", "values", "items", "result",
)

_STRUCT_DOUBLE = struct.Struct("<d")


class WireCodecError(ValueError):
    """Raised on any malformed, truncated or unsupported wire frame.

    The worker maps this to HTTP 400 (never a crash, never a dead
    replica); the transport maps a response-side decode failure to a
    ShardError on that one request.
    """


def zstd_available() -> bool:
    return _zstd is not None


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if -(1 << 63) <= n < (1 << 63) else _raise_int(n)


def _raise_int(n: int) -> int:
    raise WireCodecError(f"int out of 64-bit range: {n}")


def _unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def _write_varint(out: io.BytesIO, u: int) -> None:
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


class _Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0
        self.end = len(buf)

    def read_varint(self) -> int:
        u = 0
        shift = 0
        buf, pos, end = self.buf, self.pos, self.end
        while True:
            if pos >= end:
                raise WireCodecError("truncated varint")
            b = buf[pos]
            pos += 1
            u |= (b & 0x7F) << shift
            if not b & 0x80:
                self.pos = pos
                return u
            shift += 7
            if shift > 70:
                raise WireCodecError("varint too long")

    def read_bytes(self, n: int) -> bytes:
        pos = self.pos
        if n < 0 or pos + n > self.end:
            raise WireCodecError("truncated frame body")
        self.pos = pos + n
        return self.buf[pos : pos + n]

    def read_byte(self) -> int:
        pos = self.pos
        if pos >= self.end:
            raise WireCodecError("truncated frame body")
        self.pos = pos + 1
        return self.buf[pos]


class _Encoder:
    """One frame's encode pass: intern table is per-frame (stateless
    across requests, so worker restarts need no codec re-sync)."""

    __slots__ = ("out", "interned")

    def __init__(self) -> None:
        self.out = io.BytesIO()
        self.interned: Dict[str, int] = {
            s: i for i, s in enumerate(_PRESET_STRINGS)
        }

    def encode_value(self, v: Any) -> None:
        out = self.out
        if v is None:
            out.write(b"\x00")
        elif v is True:
            out.write(b"\x01")
        elif v is False:
            out.write(b"\x02")
        elif type(v) is int:
            out.write(b"\x03")
            _write_varint(out, _zigzag(v))
        elif type(v) is float:
            out.write(b"\x04")
            out.write(_STRUCT_DOUBLE.pack(v))
        elif type(v) is str:
            self._encode_str(v)
        elif type(v) is list:
            self._encode_list(v)
        elif type(v) is dict:
            self._encode_dict(v)
        elif isinstance(v, bool):  # bool subclass guard (unreachable for
            out.write(b"\x01" if v else b"\x02")  # real json input)
        elif isinstance(v, int):
            out.write(b"\x03")
            _write_varint(out, _zigzag(int(v)))
        elif isinstance(v, float):
            out.write(b"\x04")
            out.write(_STRUCT_DOUBLE.pack(float(v)))
        elif isinstance(v, str):
            self._encode_str(str(v))
        elif isinstance(v, (list, tuple)):
            self._encode_list(list(v))
        elif isinstance(v, dict):
            self._encode_dict(dict(v))
        else:
            raise WireCodecError(
                f"unencodable type on the wire: {type(v).__name__}"
            )

    def _encode_str(self, s: str) -> None:
        out = self.out
        ref = self.interned.get(s)
        if ref is not None:
            out.write(b"\x06")
            _write_varint(out, ref)
            return
        raw = s.encode("utf-8")
        if len(raw) <= _INTERN_MAX:
            self.interned[s] = len(self.interned)
            out.write(b"\x05")
        else:
            out.write(b"\x07")
        _write_varint(out, len(raw))
        out.write(raw)

    def _encode_list(self, v: List[Any]) -> None:
        out = self.out
        # Per-op key table: a non-trivial list of dicts sharing one key
        # tuple encodes schema-once/rows-after. The hot wave bodies
        # (fleet batches, pod lists, alloc deltas) all hit this path.
        if len(v) >= 2 and type(v[0]) is dict and v[0]:
            keys = tuple(v[0].keys())
            homogeneous = True
            for item in v:
                if type(item) is not dict or tuple(item.keys()) != keys:
                    homogeneous = False
                    break
            if homogeneous:
                out.write(b"\x0a")
                _write_varint(out, len(keys))
                for k in keys:
                    if type(k) is not str:
                        raise WireCodecError("non-string dict key")
                    self._encode_str(k)
                _write_varint(out, len(v))
                for item in v:
                    for k in keys:
                        self.encode_value(item[k])
                return
        out.write(b"\x08")
        _write_varint(out, len(v))
        for item in v:
            self.encode_value(item)

    def _encode_dict(self, v: Dict[str, Any]) -> None:
        out = self.out
        out.write(b"\x09")
        _write_varint(out, len(v))
        for k, val in v.items():
            if type(k) is not str:
                raise WireCodecError("non-string dict key")
            self._encode_str(k)
            self.encode_value(val)


class _Decoder:
    __slots__ = ("r", "interned")

    def __init__(self, buf: bytes) -> None:
        self.r = _Reader(buf)
        self.interned: List[str] = list(_PRESET_STRINGS)

    def decode_value(self) -> Any:
        r = self.r
        tag = r.read_byte()
        if tag == _T_NONE:
            return None
        if tag == _T_TRUE:
            return True
        if tag == _T_FALSE:
            return False
        if tag == _T_INT:
            return _unzigzag(r.read_varint())
        if tag == _T_FLOAT:
            return _STRUCT_DOUBLE.unpack(r.read_bytes(8))[0]
        if tag in (_T_STR_NEW, _T_STR_BIG):
            n = r.read_varint()
            try:
                s = r.read_bytes(n).decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireCodecError(f"bad utf-8 in string: {e}") from e
            if tag == _T_STR_NEW:
                if len(s.encode("utf-8")) > _INTERN_MAX:
                    raise WireCodecError("oversized interned string")
                self.interned.append(s)
            return s
        if tag == _T_STR_REF:
            ref = r.read_varint()
            if ref >= len(self.interned):
                raise WireCodecError(f"dangling string ref {ref}")
            return self.interned[ref]
        if tag == _T_LIST:
            n = r.read_varint()
            if n > r.end - r.pos:  # each element costs >= 1 byte
                raise WireCodecError("list count exceeds frame")
            return [self.decode_value() for _ in range(n)]
        if tag == _T_DICT:
            n = r.read_varint()
            if n * 2 > r.end - r.pos:
                raise WireCodecError("dict count exceeds frame")
            d: Dict[str, Any] = {}
            for _ in range(n):
                k = self.decode_value()
                if type(k) is not str:
                    raise WireCodecError("non-string dict key on decode")
                d[k] = self.decode_value()
            return d
        if tag == _T_TABLE:
            ncols = r.read_varint()
            if ncols == 0 or ncols > r.end - r.pos:
                raise WireCodecError("bad table schema")
            keys = []
            for _ in range(ncols):
                k = self.decode_value()
                if type(k) is not str:
                    raise WireCodecError("non-string table key")
                keys.append(k)
            nrows = r.read_varint()
            if nrows * ncols > r.end - r.pos:
                raise WireCodecError("table rows exceed frame")
            rows = []
            for _ in range(nrows):
                rows.append({k: self.decode_value() for k in keys})
            return rows
        raise WireCodecError(f"unknown value tag {tag}")


def encode_frame(obj: Any, compress_min_bytes: int = 1024) -> Tuple[bytes, int]:
    """Encode *obj* into a TKW1 frame.

    Returns ``(frame, raw_len)`` where *raw_len* is the pre-compression
    payload size — the wire accounting uses it to report bytes saved and
    the per-op compression ratio without re-serializing to JSON.
    Payloads at or above *compress_min_bytes* are compressed (zstd when
    the stdlib has it, zlib level 1 otherwise) but kept raw if
    compression doesn't actually shrink them.
    """
    enc = _Encoder()
    enc.encode_value(obj)
    raw = enc.out.getvalue()
    flag = _FLAG_RAW
    payload = raw
    if compress_min_bytes >= 0 and len(raw) >= compress_min_bytes:
        if _zstd is not None:
            comp = _zstd.compress(raw, 1)
            cflag = _FLAG_ZSTD
        else:
            comp = zlib.compress(raw, 1)
            cflag = _FLAG_ZLIB
        if len(comp) < len(raw):
            payload = comp
            flag = cflag
    return _MAGIC + bytes((flag,)) + payload, len(raw)


def decode_frame(frame: bytes) -> Any:
    """Decode a TKW1 frame back to the exact object that was encoded.

    Raises :class:`WireCodecError` on anything malformed — wrong magic,
    unknown flags, truncated or trailing bytes, corrupt payload.
    """
    return decode_frame_ex(frame)[0]


def decode_frame_ex(frame: bytes) -> Tuple[Any, int]:
    """Like :func:`decode_frame` but also returns the pre-compression
    payload size, which the transport's wire accounting reports as the
    per-op ``raw`` bytes next to what actually crossed the socket."""
    if len(frame) < 6:
        raise WireCodecError("frame too short")
    if frame[:4] != _MAGIC:
        raise WireCodecError(f"bad magic {frame[:4]!r}")
    flag = frame[4]
    payload = frame[5:]
    if flag == _FLAG_ZLIB:
        try:
            payload = zlib.decompress(payload)
        except zlib.error as e:
            raise WireCodecError(f"zlib payload corrupt: {e}") from e
    elif flag == _FLAG_ZSTD:
        if _zstd is None:
            raise WireCodecError("zstd frame but no zstd support")
        try:
            payload = _zstd.decompress(payload)
        except Exception as e:
            raise WireCodecError(f"zstd payload corrupt: {e}") from e
    elif flag != _FLAG_RAW:
        raise WireCodecError(f"unknown frame flags {flag}")
    dec = _Decoder(payload)
    obj = dec.decode_value()
    if dec.r.pos != dec.r.end:
        raise WireCodecError(
            f"{dec.r.end - dec.r.pos} trailing bytes after value"
        )
    return obj, len(payload)


def dumps_json(obj: Any) -> bytes:
    """Compact JSON body — the codec-off wire path (and the oracle)."""
    return json.dumps(obj, separators=JSON_SEPARATORS).encode("utf-8")

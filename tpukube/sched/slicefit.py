"""slicefit — contiguous sub-slice search in a partially occupied ICI mesh.

The algorithmic core of the scheduler (SURVEY.md §2 C7, §9.3 "the hard
parts"). The reference's ``grpalloc`` tree-matches grouped GPU requests
against a node's NVLink/PCIe topology tree; the TPU analog is geometric:
find an axis-aligned sub-box of the chip mesh whose chips are all free,
sized (or shaped) for the gang, and score candidates so that

  * the gang gets a compact box (low surface area => short ICI paths and
    good bisection bandwidth for XLA collectives), and
  * the cluster keeps its free space defensible (corner/wall packing =>
    low fragmentation for future gangs).

Implementation: numpy occupancy voxel grid + a 3D summed-area table, so
testing "is this box fully free" is O(1) per origin and a full shape sweep
is O(X*Y*Z). Exact search with deterministic tie-breaking — mesh sizes in
scope (<= a few thousand chips) make exact affordable (SURVEY.md §9.3).

Torus axes are honored: on a wraparound axis the free grid is tiled so box
origins may wrap (a (3,1,1) slice at x in {3,0,1} of a 4-torus is
contiguous over ICI), and boundary "wall contact" is only credited on
non-torus axes (a torus has no walls).

Irregular fallback: when no box of the requested volume exists (e.g. a
5-pod gang on a 4x4 mesh), ``find_slice(..., allow_irregular=True)`` grows
a connected free region instead — gangs still land ICI-connected, just not
box-shaped. Disabled by default; the extender decides policy.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import lru_cache
from typing import Iterable, Optional

import numpy as np

from tpukube.core.mesh import Box, MeshSpec, factor_shapes, surface
from tpukube.core.types import Link, TopologyCoord, canonical_link

Shape = tuple[int, int, int]


def point_contact(mesh: MeshSpec, c: TopologyCoord, blocked) -> int:
    """Contact of one chip against blocked neighbors and mesh walls — the
    single definition of single-chip snugness. ``blocked(coord) -> bool``
    says whether a neighbor counts as contact; true mesh walls always do
    (axes of extent 1 contribute both walls; a length-2 torus axis reaches
    the same chip in both directions and both count, matching the box
    sweep's per-face slab sampling). Shared by _Sweep.contact_point
    (occupancy-grid form) and the extender's single-chip placement fast
    path (free-set form)."""
    total = 0
    for axis in range(3):
        d = mesh.dims[axis]
        wrap = mesh.torus[axis] and d > 1
        for step in (-1, 1):
            idx = c[axis] + step
            if wrap:
                v = list(c)
                v[axis] = idx % d
                if blocked(TopologyCoord(*v)):
                    total += 1
            elif idx < 0 or idx >= d:
                total += 1  # true mesh wall
            else:
                v = list(c)
                v[axis] = idx
                if blocked(TopologyCoord(*v)):
                    total += 1
    return total


def coords_break_link(chips: set[TopologyCoord], broken: set[Link]) -> bool:
    """True if both endpoints of any downed ICI link are in ``chips``.

    A slice containing both ends of a dead link is degraded no matter its
    geometry — XLA collectives route over mesh adjacency, so the link WILL
    carry traffic. Containment (not just internal adjacency) is the test.
    The single source of this predicate; gang sweep and placement share it.
    """
    return any(a in chips and b in chips for a, b in broken)


def box_breaks_link(
    mesh: MeshSpec, box: Box, broken: set[Link]
) -> bool:
    """``coords_break_link`` specialized to an (optionally torus-wrapped)
    box, O(|broken|) interval checks — this runs per candidate origin in the
    sweep hot loop, so no coord-set materialization."""
    if not broken:
        return False
    o, s, dims = box.origin, box.shape, mesh.dims

    def inside(p: TopologyCoord) -> bool:
        # (p - origin) mod dim < extent is exact for wrapped boxes on torus
        # axes and, because in-mesh non-torus boxes never wrap, for plain
        # axes too (the mod only bites when the box wraps).
        return all((p[i] - o[i]) % dims[i] < s[i] for i in range(3))

    return any(inside(a) and inside(b) for a, b in broken)


def occupancy_grid(mesh: MeshSpec, occupied: Iterable[TopologyCoord]) -> np.ndarray:
    """Boolean [X, Y, Z] grid, True = occupied/unavailable.

    A prebuilt boolean ndarray passes through unchanged (hot path: callers
    that already hold a grid skip the per-coord rebuild)."""
    if isinstance(occupied, np.ndarray):
        if occupied.shape != mesh.dims:
            raise ValueError(
                f"occupancy grid shape {occupied.shape} != mesh {mesh.dims}"
            )
        return occupied.astype(bool, copy=False)
    grid = np.zeros(mesh.dims, dtype=bool)
    for c in occupied:
        if not mesh.contains(TopologyCoord.of(c)):
            raise ValueError(f"occupied coord {c} outside mesh {mesh.dims}")
        grid[tuple(c)] = True
    return grid


def box_coords(mesh: MeshSpec, box: Box) -> list[TopologyCoord]:
    """Chips of a box, wrapping on torus axes (origin is always in-mesh)."""
    return [
        TopologyCoord(*(v % d for v, d in zip(c, mesh.dims)))
        for c in box.coords()
    ]


class _Sweep:
    """One occupancy snapshot prepared for repeated box queries: the free
    grid tiled along torus axes (so wrapped origins become plain origins)
    plus its zero-padded summed-area table.

    Also the FREE-BOX INDEX of the epoch-cached scheduling snapshot
    (sched/snapshot.py): ``origins``/``contacts`` results are memoized
    per shape, so a sweep reused across webhook cycles answers repeat
    shape queries from the index instead of re-scanning."""

    def __init__(self, mesh: MeshSpec, grid: np.ndarray):
        if grid.shape != mesh.dims:
            raise ValueError(f"grid shape {grid.shape} != mesh dims {mesh.dims}")
        self.mesh = mesh
        self.grid = grid
        free = ~grid
        ext = free
        for axis in range(3):
            d = mesh.dims[axis]
            if mesh.torus[axis] and d > 1:
                # tile by d-1 so any box of extent <= d can start anywhere
                wrap = ext.take(range(0, d - 1), axis=axis)
                ext = np.concatenate([ext, wrap], axis=axis)
        self.ext_free = ext
        sat = np.zeros(tuple(s + 1 for s in ext.shape), dtype=np.int64)
        sat[1:, 1:, 1:] = ext.astype(np.int64).cumsum(0).cumsum(1).cumsum(2)
        self.sat = sat
        # free-box index: shape -> origins / per-origin contact arrays
        self._origins_cache: dict[Shape, np.ndarray] = {}
        self._contacts_cache: dict[Shape, np.ndarray] = {}

    def origins(self, shape: Shape) -> np.ndarray:
        """[N, 3] origins (in-mesh) where a `shape` box is entirely free,
        wrapping over torus axes. Lexicographic order; full-extent boxes on
        a torus axis are canonicalized to origin 0 (all origins would name
        the same chip set). Memoized per shape — callers must not mutate
        the returned array."""
        cached = self._origins_cache.get(shape)
        if cached is not None:
            return cached
        out = self._compute_origins(shape)
        self._origins_cache[shape] = out
        return out

    def _compute_origins(self, shape: Shape) -> np.ndarray:
        s = self.sat
        a, b, c = shape
        dims = self.mesh.dims
        for extent, d in zip(shape, dims):
            if extent > d:
                return np.empty((0, 3), dtype=int)
        eX, eY, eZ = self.ext_free.shape
        if a > eX or b > eY or c > eZ:
            return np.empty((0, 3), dtype=int)
        vol = (
            s[a:, b:, c:]
            - s[:-a, b:, c:]
            - s[a:, :-b, c:]
            - s[a:, b:, :-c]
            + s[:-a, :-b, c:]
            + s[:-a, b:, :-c]
            + s[a:, :-b, :-c]
            - s[:-a, :-b, :-c]
        )
        origins = np.argwhere(vol == a * b * c)
        if origins.size == 0:
            return origins
        # keep origins that are in-mesh and legal for each axis
        keep = np.ones(len(origins), dtype=bool)
        for axis, extent in enumerate(shape):
            d = dims[axis]
            if self.mesh.torus[axis] and d > 1:
                if extent == d:
                    keep &= origins[:, axis] == 0
                else:
                    keep &= origins[:, axis] < d
            else:
                keep &= origins[:, axis] <= d - extent
        return origins[keep]

    def contact_point(self, c: TopologyCoord) -> int:
        """``contact`` specialized to a single chip (1x1x1 box) — the
        per-chip snugness loop of /prioritize calls this per node per pod,
        where the general slab machinery below is ~10x the cost."""
        g = self.grid
        return point_contact(self.mesh, c, lambda nb: bool(g[nb]))

    def contact_grid(self) -> np.ndarray:
        """Per-chip contact against this grid for EVERY mesh cell at once —
        one vectorized stencil replaces a Python point_contact per chip
        when a webhook scores hundreds of nodes. Cached per sweep; must
        agree cell-for-cell with contact_point (tested)."""
        cached = getattr(self, "_contact_grid", None)
        if cached is not None:
            return cached
        g = self.grid.astype(np.int16)
        out = np.zeros(g.shape, np.int16)
        for axis in range(3):
            d = g.shape[axis]
            if self.mesh.torus[axis] and d > 1:
                out += np.roll(g, 1, axis=axis) + np.roll(g, -1, axis=axis)
                continue
            lo = [slice(None)] * 3
            hi = [slice(None)] * 3
            # -1 neighbor: wall on plane 0, shifted occupancy elsewhere
            lo[axis] = 0
            out[tuple(lo)] += 1
            if d > 1:
                dst, src = [slice(None)] * 3, [slice(None)] * 3
                dst[axis], src[axis] = slice(1, None), slice(0, -1)
                out[tuple(dst)] += g[tuple(src)]
            # +1 neighbor: wall on plane d-1, shifted occupancy elsewhere
            hi[axis] = d - 1
            out[tuple(hi)] += 1
            if d > 1:
                dst, src = [slice(None)] * 3, [slice(None)] * 3
                dst[axis], src[axis] = slice(0, -1), slice(1, None)
                out[tuple(dst)] += g[tuple(src)]
        self._contact_grid = out
        return out

    def _box_free(self, starts: np.ndarray, shape: Shape) -> np.ndarray:
        """Free-chip count of a ``shape`` box at every start in
        ``starts`` ([N, 3], ext-grid coordinates) — one vectorized
        8-corner gather over the summed-area table, no per-origin loop."""
        s = self.sat
        x0, y0, z0 = starts[:, 0], starts[:, 1], starts[:, 2]
        x1, y1, z1 = x0 + shape[0], y0 + shape[1], z0 + shape[2]
        return (
            s[x1, y1, z1] - s[x0, y1, z1] - s[x1, y0, z1] - s[x1, y1, z0]
            + s[x0, y0, z1] + s[x0, y1, z0] + s[x1, y0, z0] - s[x0, y0, z0]
        )

    def contacts(self, shape: Shape) -> np.ndarray:
        """``contact`` for EVERY free origin of ``shape`` at once (aligned
        with ``origins(shape)``): each face's adjacent slab is itself a
        box, so its occupied count is (slab area - free count) read off
        the same integral image — the whole shape tier scores in a
        handful of numpy gathers instead of a per-origin Python loop.
        Must agree entry-for-entry with ``contact`` (property-tested)."""
        cached = self._contacts_cache.get(shape)
        if cached is not None:
            return cached
        origins = self.origins(shape)
        total = np.zeros(len(origins), dtype=np.int64)
        dims = self.mesh.dims
        for axis in range(3):
            if len(origins) == 0:
                break
            d = dims[axis]
            extent = shape[axis]
            slab = list(shape)
            slab[axis] = 1
            slab_shape = (slab[0], slab[1], slab[2])
            area = slab[0] * slab[1] * slab[2]  # face area
            axv = origins[:, axis]
            if self.mesh.torus[axis] and d > 1:
                if extent == d:
                    continue  # box spans the whole ring: no face
                lo = origins.copy()
                lo[:, axis] = (axv - 1) % d
                hi = origins.copy()
                hi[:, axis] = axv + extent  # <= 2d-2, inside the tiling
                total += area - self._box_free(lo, slab_shape)
                total += area - self._box_free(hi, slab_shape)
            else:
                wall_lo = axv == 0
                lo = origins.copy()
                lo[:, axis] = np.maximum(axv - 1, 0)  # clamp; walls masked
                total += np.where(
                    wall_lo, area, area - self._box_free(lo, slab_shape)
                )
                wall_hi = axv + extent >= d
                hi = origins.copy()
                hi[:, axis] = np.minimum(axv + extent, d - 1)
                total += np.where(
                    wall_hi, area, area - self._box_free(hi, slab_shape)
                )
        self._contacts_cache[shape] = total
        return total

    def contact(self, box: Box) -> int:
        """Faces of the box touching a mesh wall or occupied chips.

        Higher contact = snugger placement = less fragmentation of the
        remaining free space (3D best-fit/corner packing). Wall credit only
        exists on non-torus axes; on torus axes the adjacent slab is taken
        modulo the dimension.
        """
        if box.shape == (1, 1, 1):
            return self.contact_point(TopologyCoord.of(box.origin))
        g = self.grid
        mesh = self.mesh
        X, Y, Z = g.shape
        (ox, oy, oz), (sx, sy, sz) = box.origin, box.shape

        def ax_idx(vals, d):
            return np.asarray(vals) % d

        xs = ax_idx(range(ox, ox + sx), X)
        ys = ax_idx(range(oy, oy + sy), Y)
        zs = ax_idx(range(oz, oz + sz), Z)
        total = 0
        # (axis, face_lo, slab_index, face_area, plane_sel)
        faces = [
            (0, ox - 1, ox + sx, sy * sz, np.ix_(ys, zs)),
            (1, oy - 1, oy + sy, sx * sz, np.ix_(xs, zs)),
            (2, oz - 1, oz + sz, sx * sy, np.ix_(xs, ys)),
        ]
        for axis, lo, hi, area, sel in faces:
            d = g.shape[axis]
            extent = box.shape[axis]
            for idx in (lo, hi):
                if mesh.torus[axis] and d > 1:
                    if extent == d:
                        continue  # box spans the whole ring: no face
                    slab = np.take(g, idx % d, axis=axis)
                    total += int(slab[sel].sum())
                else:
                    if idx < 0 or idx >= d:
                        total += area  # true mesh wall
                    else:
                        slab = np.take(g, idx, axis=axis)
                        total += int(slab[sel].sum())
        return total


@dataclass(frozen=True)
class ScoredBox:
    box: Box
    # Lower is better on each component, compared in order:
    surface: int       # box surface area — gang-internal ICI compactness
    contact: int       # NEGATED wall/occupied contact — cluster packing
    origin_key: Shape  # deterministic final tie-break

    @property
    def sort_key(self) -> tuple:
        return (self.surface, self.contact, self.origin_key)


@lru_cache(maxsize=4096)
def _candidate_shapes_for(
    dims: Shape, count: Optional[int], shape: Optional[Shape]
) -> tuple[Shape, ...]:
    """Memoized shape enumeration: the candidate list depends only on
    (mesh dims, count, shape), and the same handful of requests repeats
    on every webhook — re-factoring the volume each time was measurable
    on the filter/prioritize microbench."""
    if shape is not None:
        perms = sorted(set(itertools.permutations(shape)))
        return tuple(
            p for p in perms if all(s <= d for s, d in zip(p, dims))
        )
    assert count is not None
    return tuple(factor_shapes(count, dims))  # already compactness-sorted


def _candidate_shapes(
    mesh: MeshSpec, count: Optional[int], shape: Optional[Shape]
) -> tuple[Shape, ...]:
    """Shapes to sweep, most-preferred first.

    A pinned shape is honored up to axis permutation (a 4x4x1 request is
    geometrically the same slice as 1x4x4; jobs index their mesh axes
    logically, the physical orientation is the scheduler's choice).
    """
    return _candidate_shapes_for(
        mesh.dims, count, None if shape is None else tuple(shape)
    )


def _validate_request(count: Optional[int], shape: Optional[Shape]) -> None:
    if (count is None) == (shape is None):
        raise ValueError("exactly one of count/shape must be given")
    if count is not None and count < 1:
        raise ValueError(f"count must be >= 1, got {count}")
    if shape is not None and any(s < 1 for s in shape):
        raise ValueError(f"shape dims must be >= 1, got {shape}")


def _boxes_clear_of_links(
    dims: Shape, origins: np.ndarray, shape: Shape, broken: set[Link]
) -> np.ndarray:
    """Boolean keep-mask over ``origins``: False where a ``shape`` box at
    that origin contains BOTH endpoints of a downed link — the batched
    form of ``box_breaks_link`` (same wrapped-interval test, one numpy
    comparison per link instead of a per-origin Python call)."""
    keep = np.ones(len(origins), dtype=bool)
    dims_a = np.asarray(dims)
    shape_a = np.asarray(shape)
    for a, b in broken:
        in_a = np.all((np.asarray(a) - origins) % dims_a < shape_a, axis=1)
        in_b = np.all((np.asarray(b) - origins) % dims_a < shape_a, axis=1)
        keep &= ~(in_a & in_b)
    return keep


def iter_free_boxes_in(
    sweep: _Sweep,
    count: Optional[int] = None,
    shape: Optional[Shape] = None,
    broken: Optional[set[Link]] = None,
) -> Iterable[ScoredBox]:
    """``iter_free_boxes`` over a PREPARED sweep (the snapshot fast
    path): origins and contact scores come batched per shape tier from
    the sweep's free-box index; only the yield loop is Python."""
    _validate_request(count, shape)
    mesh = sweep.mesh
    for shp in _candidate_shapes(mesh, count, shape):
        origins = sweep.origins(shp)
        if len(origins) == 0:
            continue
        contacts = sweep.contacts(shp)
        if broken:
            keep = _boxes_clear_of_links(mesh.dims, origins, shp, broken)
            origins, contacts = origins[keep], contacts[keep]
        s = surface(shp)
        for origin, contact in zip(origins, contacts):
            ok = (int(origin[0]), int(origin[1]), int(origin[2]))
            yield ScoredBox(
                box=Box(TopologyCoord(*ok), shp),
                surface=s,
                contact=-int(contact),
                origin_key=ok,
            )


def iter_free_boxes(
    mesh: MeshSpec,
    grid: np.ndarray,
    count: Optional[int] = None,
    shape: Optional[Shape] = None,
    broken: Optional[set[Link]] = None,
) -> Iterable[ScoredBox]:
    """All fully-free boxes matching the request, scored, unsorted.
    Boxes spanning a downed ICI link (``broken``) are excluded.
    Thin wrapper: callers holding a scheduling snapshot use
    ``iter_free_boxes_in`` and skip the per-call sweep build."""
    return iter_free_boxes_in(_Sweep(mesh, grid), count=count,
                              shape=shape, broken=broken)


def find_slice_in(
    sweep: _Sweep,
    count: Optional[int] = None,
    shape: Optional[Shape] = None,
    allow_irregular: bool = False,
    broken: Optional[set[Link]] = None,
) -> Optional[list[TopologyCoord]]:
    """``find_slice`` over a PREPARED sweep — the snapshot fast path.

    The all-free test for every origin of a shape is one integral-image
    subtraction (``_Sweep.origins``), contact scoring is batched per
    shape tier (``_Sweep.contacts``), and the best candidate of a tier
    falls out of one ``lexsort`` — no per-origin Python loop anywhere.
    Selection order is bit-identical to the reference sweep: surface
    strictly dominates, then max contact, then lexicographic origin,
    first shape in candidate order winning ties.
    """
    _validate_request(count, shape)
    mesh = sweep.mesh
    best_key: Optional[tuple] = None
    best_box: Optional[Box] = None
    tier: Optional[int] = None
    for shp in _candidate_shapes(mesh, count, shape):
        s = surface(shp)
        if tier is not None and s > tier:
            break  # strictly worse tier; current best cannot be beaten
        origins = sweep.origins(shp)
        if len(origins) == 0:
            continue
        contacts = sweep.contacts(shp)
        if broken:
            keep = _boxes_clear_of_links(mesh.dims, origins, shp, broken)
            origins, contacts = origins[keep], contacts[keep]
            if len(origins) == 0:
                continue
        # best of this tier: max contact, then lexicographic origin
        # (lexsort keys are minor-to-major, so -contacts is primary)
        i = int(np.lexsort(
            (origins[:, 2], origins[:, 1], origins[:, 0], -contacts)
        )[0])
        key = (
            s,
            -int(contacts[i]),
            (int(origins[i, 0]), int(origins[i, 1]), int(origins[i, 2])),
        )
        if best_key is None or key < best_key:
            best_key = key
            best_box = Box(TopologyCoord(*key[2]), shp)
            tier = s
    if best_box is not None:
        return box_coords(mesh, best_box)
    if allow_irregular and shape is None and count is not None:
        return _find_connected(mesh, sweep.grid, count, broken)
    return None


def find_slice(
    mesh: MeshSpec,
    occupied: Iterable[TopologyCoord],
    count: Optional[int] = None,
    shape: Optional[Shape] = None,
    allow_irregular: bool = False,
    broken: Optional[set[Link]] = None,
) -> Optional[list[TopologyCoord]]:
    """Best placement for a gang: the chips of the best free box, or (with
    ``allow_irregular``) a connected free region when no box exists.

    Returns None when the request cannot be satisfied at all. Candidates
    spanning a downed ICI link (``broken``, canonical pairs) are rejected.

    Surface area strictly dominates the score, so the sweep stops after the
    first surface tier that yields any candidate — worse-surface shapes can
    never win and are not scored (the scheduler's hot path).

    Thin wrapper: builds one throwaway sweep. Callers with a scheduling
    snapshot (sched/snapshot.py) use ``find_slice_in`` on its cached
    sweep instead.
    """
    _validate_request(count, shape)
    sweep = _Sweep(mesh, occupancy_grid(mesh, occupied))
    return find_slice_in(sweep, count=count, shape=shape,
                         allow_irregular=allow_irregular, broken=broken)


def _find_connected(
    mesh: MeshSpec, grid: np.ndarray, count: int,
    broken: Optional[set[Link]] = None,
) -> Optional[list[TopologyCoord]]:
    """Greedy connected-region growth over free chips (BFS from the most
    wall-adjacent free chip, preferring frontier chips with max contact).
    Deterministic. Used only when no box of volume ``count`` exists.
    Growth never crosses a downed link, and never ADDS a chip that would
    put both endpoints of a downed link inside the region (a region
    containing both ends of a dead link is degraded even when they joined
    through live paths — same containment rule as ``box_breaks_link``)."""
    free = {TopologyCoord(*map(int, idx)) for idx in np.argwhere(~grid)}
    if len(free) < count:
        return None
    broken = broken or set()

    def live(a: TopologyCoord, b: TopologyCoord) -> bool:
        return not broken or canonical_link(a, b) not in broken

    def degrades(c: TopologyCoord, chosen: set[TopologyCoord]) -> bool:
        return any(
            (c == a and b in chosen) or (c == b and a in chosen)
            for a, b in broken
        )

    def isolation(c: TopologyCoord) -> int:
        return -sum(1 for nb in mesh.neighbors(c) if nb in free and live(c, nb))

    # try seeds in decreasing wall/occupied-contact order; first success wins
    seeds = sorted(free, key=lambda c: (isolation(c), tuple(c)))
    for seed in seeds:
        region = [seed]
        chosen = {seed}
        while len(region) < count:
            frontier = [
                nb
                for r in region
                for nb in mesh.neighbors(r)
                if nb in free and nb not in chosen and live(r, nb)
                and not degrades(nb, chosen)
            ]
            if not frontier:
                break
            # prefer the frontier chip most connected to the region
            nxt = max(
                frontier,
                key=lambda c: (
                    sum(
                        1 for nb in mesh.neighbors(c)
                        if nb in chosen and live(c, nb)
                    ),
                    tuple(-v for v in c),
                ),
            )
            region.append(nxt)
            chosen.add(nxt)
        if len(region) == count:
            return region
    return None


def largest_free_box_in(sweep: _Sweep) -> int:
    """Volume of the largest fully-free box over a prepared sweep.

    Feasibility is monotone in each extent (a free (a, b, c) box
    contains a free (a, b, c-1) box), so for each (a, b) pair the
    maximal feasible third extent is found by BINARY search —
    O(X·Y·log Z) origin queries instead of the O(X·Y·Z) descending
    scan, which at the 10k-node meshes (32×32×40) made every
    fragmentation render a multi-thousand-tier sweep. Results are
    identical to the exhaustive scan (property-tested); repeated calls
    on a cached snapshot sweep answer from memoized origins."""
    best = 0
    X, Y, Z = sweep.mesh.dims
    for a in range(1, X + 1):
        if a * Y * Z <= best:
            continue
        for b in range(1, Y + 1):
            if a * b * Z <= best:
                continue
            # smallest c that would beat the best so far; probe it
            # first — if even that fails, no c can improve on (a, b)
            lo = best // (a * b) + 1
            if lo > Z or not len(sweep.origins((a, b, lo))):
                continue
            hi = Z
            while lo < hi:  # largest feasible c, by bisection
                mid = (lo + hi + 1) // 2
                if len(sweep.origins((a, b, mid))):
                    lo = mid
                else:
                    hi = mid - 1
            best = a * b * lo
    return best


def largest_free_box(mesh: MeshSpec, grid: np.ndarray) -> int:
    """Thin wrapper (one throwaway sweep); snapshot holders use
    ``SliceSnapshot.largest_free_box`` which memoizes per epoch."""
    return largest_free_box_in(_Sweep(mesh, grid))


def fragmentation(mesh: MeshSpec, occupied: Iterable[TopologyCoord]) -> float:
    """Free-space fragmentation in [0, 1]: 1 - (largest free box)/(free chips).

    0 = all free chips form one perfect box; -> 1 as free space shatters.
    Exported to metrics and used by tests to validate packing behavior.
    Thin wrapper: the /statusz + /metrics renders read the epoch-cached
    ``SliceSnapshot.fragmentation`` instead of rebuilding a sweep per
    scrape.
    """
    grid = occupancy_grid(mesh, occupied)
    free_count = int((~grid).sum())
    if free_count == 0:
        return 0.0
    return 1.0 - largest_free_box_in(_Sweep(mesh, grid)) / free_count

"""Batched scheduling cycles (ISSUE 8 tentpole).

The extender protocol is per-pod: kube-scheduler sends /filter,
/prioritize, and /bind for one pod at a time, and the legacy path
re-plans inside every webhook. After the epoch-cached snapshot (PR 5)
removed the compute hot path, the residual wall is per-pod overhead —
three webhook round-trips each redoing overlapping work.

:class:`SchedulingCycle` turns that into kube-scheduler's
snapshot-per-cycle model, batched:

  * pending pods are ADMITTED into a scheduling queue — by their own
    /filter webhook, or ahead of time by the pod informer / sim batch
    driver (:meth:`enqueue`);
  * a CYCLE drains the queue (priority- and gang-aware order, capped at
    ``batch_max_pods``) and plans every pod against ONE epoch-pinned
    :class:`~tpukube.sched.snapshot.ClusterSnapshot`, committing each
    planned placement to the ledger as an ASSUMED allocation (the
    kube-scheduler assume-cache move) so later pods in the batch see
    earlier ones exactly as the sequential per-pod path would;
  * /filter, /prioritize, and /bind then ANSWER FROM THE PLAN — a dict
    lookup — instead of re-planning; /bind consumes the assumed
    allocation (or undoes it and falls back to the legacy path when the
    scheduler picked a different node than planned).

Placement parity is a hard contract, enforced by tests/test_cycle.py:
with batching on, every placement decision (node, chips, preemption
plan, DCN split) is bit-identical to the legacy per-pod path, because
the planner either runs the SAME per-pod code (gang / vTPU /
multi-chip pods — the "general path") or a fast path proven equal to
it (single whole-chip pods under topology scoring — the common churn
shape, planned incrementally against a cycle-local overlay so a
thousand-pod batch costs one snapshot build, not a thousand).

Locking: the cycle is owned by the Extender and ONLY touched under its
decision lock (handle() routes every webhook through it), so the plan
needs no lock of its own. The pinned snapshot is taken once per cycle
through the one seam ``_pin_snapshot`` — tpukube-lint's
snapshot-discipline pass forbids any other ``SnapshotCache`` read or
ad-hoc sweep construction in this module, so batch-plan consumers
cannot quietly fork their own view of the cluster.
"""

from __future__ import annotations

import heapq
import logging
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Optional

from tpukube.core import codec
from tpukube.core.types import (
    RESOURCE_TPU,
    AllocResult,
    PodInfo,
    TopologyCoord,
    make_device_id,
)
from tpukube.obs.registry import Histogram
from tpukube.sched import slicefit
from tpukube.sched.gang import GangError, GangManager, NoSliceError
from tpukube.sched.state import StateError

log = logging.getLogger("tpukube.cycle")


class PodPlan:
    """One pod's planned webhook answers + (optionally) its assumed
    allocation. ``names`` is the node-name tuple the plan was computed
    against — a webhook asking about a different node set is a plan
    miss (the legacy path answers it)."""

    __slots__ = (
        "pod", "uid", "names", "feasible", "failed", "scores", "node",
        "alloc", "assumed", "bind_error", "error", "planned_at", "seq",
        "epoch_key",
    )

    def __init__(self, pod: PodInfo, names: tuple[str, ...],
                 planned_at: float, seq: int):
        self.pod = pod
        self.uid = pod.uid
        self.names = names
        #: (ledger, gang) epochs when planning finished — a NON-assumed
        #: entry is only servable while these stand still (its answer
        #: was "unschedulable"/"failed" against THAT state; the legacy
        #: path would recompute after any mutation, so must we)
        self.epoch_key: Optional[tuple[int, int]] = None
        self.feasible: Optional[list[str]] = None
        self.failed: dict[str, str] = {}
        self.scores: dict[str, int] = {}
        self.node: Optional[str] = None        # planner's predicted pick
        self.alloc: Optional[AllocResult] = None
        self.assumed = False                   # alloc committed, bind pending
        self.bind_error: Optional[str] = None  # planned /bind error answer
        self.error: Optional[str] = None       # planned /filter error answer
        self.planned_at = planned_at
        self.seq = seq


class _SliceOverlay:
    """Incremental view of one ICI slice for the fast path: the pinned
    snapshot's blocked contact values (as a plain dict over the free
    chips — numpy scalar indexing per query was the measured kilonode
    bottleneck) plus per-node free sets, updated in O(1) per placement
    instead of re-deriving O(volume) sweeps per pod. Values are proven
    equal to the legacy per-pod reads (contact_grid / point_contact /
    free-count feasibility) by tests/test_cycle.py's parity suite.

    Since ISSUE 10 the overlay is PERSISTENT across cycles: it also
    carries the mutable occupied/reserved membership sets (the union
    the contact values count against) so it can be patched from the
    snapshot cache's delta chain — blocking and unblocking chips as
    commits, releases, and reservation moves land — instead of being
    rebuilt O(chips) at the top of every cycle."""

    __slots__ = ("mesh", "contact", "free_by_node", "owner", "occ",
                 "resv", "hosts")

    def __init__(self, mesh, contact, free_by_node, owner, occ, resv,
                 hosts):
        self.mesh = mesh
        #: free coord -> its contact against the blocked set; seeded
        #: from the pinned snapshot's vectorized contact grid and
        #: mutated incrementally (blocked chips are never queried)
        self.contact = contact
        #: node -> set of free, unreserved chip coords on that node
        #: (every tracked — annotated, whole-chip-mode — node has an
        #: entry, possibly empty: membership = "tracked")
        self.free_by_node = free_by_node
        #: free coord -> owning node name (for best-score fanout)
        self.owner = owner
        #: mutable occupied / reserved membership (blocked = occ ∪ resv
        #: — the two sets may overlap: a preemption victim's chips are
        #: occupied AND inside the new reservation until eviction)
        self.occ = occ
        self.resv = resv
        #: coord -> node name for the whole slice (the ledger's shared
        #: frozen host map; host moves are full-rebuild markers)
        self.hosts = hosts

    def _blocked(self, coord: TopologyCoord) -> bool:
        return coord in self.occ or coord in self.resv

    def set_occupied(self, coord: TopologyCoord) -> set[str]:
        """An assumed/committed allocation claimed ``coord``. Returns
        the nodes whose best contact may have changed."""
        was = self._blocked(coord)
        self.occ.add(coord)
        return set() if was else self._block_effects(coord)

    def clear_occupied(self, coord: TopologyCoord) -> set[str]:
        """A release returned ``coord`` to fully-free (the ledger delta
        only emits this for healthy, zero-share chips)."""
        self.occ.discard(coord)
        return set() if self._blocked(coord) else \
            self._unblock_effects(coord)

    def set_reserved(self, coord: TopologyCoord) -> set[str]:
        was = self._blocked(coord)
        self.resv.add(coord)
        return set() if was else self._block_effects(coord)

    def clear_reserved(self, coord: TopologyCoord) -> set[str]:
        self.resv.discard(coord)
        return set() if self._blocked(coord) else \
            self._unblock_effects(coord)

    def _block_effects(self, coord: TopologyCoord) -> set[str]:
        """``coord`` just became blocked: remove it from its node's
        free set and bump each free neighbor's contact once per
        reaching direction — the exact increment
        ``slicefit.point_contact`` would observe (a length-2 torus axis
        reaches the same neighbor twice and counts twice). Returns the
        nodes whose best contact may have changed."""
        node = self.hosts.get(coord)
        free = self.free_by_node.get(node) if node is not None else None
        touched = set()
        if free is not None:
            free.discard(coord)
            touched.add(node)
        self.contact.pop(coord, None)
        self.owner.pop(coord, None)
        mesh = self.mesh
        contact = self.contact
        owner = self.owner
        for axis in range(3):
            d = mesh.dims[axis]
            wrap = mesh.torus[axis] and d > 1
            for step in (-1, 1):
                idx = coord[axis] + step
                if wrap:
                    idx %= d
                elif idx < 0 or idx >= d:
                    continue  # true wall: no neighbor to update
                v = list(coord)
                v[axis] = idx
                nb = TopologyCoord(*v)
                if nb in contact:  # a free chip whose snugness grew
                    contact[nb] += 1
                    touched.add(owner[nb])
        return touched

    def _unblock_effects(self, coord: TopologyCoord) -> set[str]:
        """``coord`` just became free: decrement each free neighbor's
        contact (the inverse of ``_block_effects``) and — when its node
        is tracked — return it to the free set with its own contact
        computed against the current blocked union."""
        mesh = self.mesh
        contact = self.contact
        owner = self.owner
        touched = set()
        for axis in range(3):
            d = mesh.dims[axis]
            wrap = mesh.torus[axis] and d > 1
            for step in (-1, 1):
                idx = coord[axis] + step
                if wrap:
                    idx %= d
                elif idx < 0 or idx >= d:
                    continue
                v = list(coord)
                v[axis] = idx
                nb = TopologyCoord(*v)
                if nb in contact:
                    contact[nb] -= 1
                    touched.add(owner[nb])
        node = self.hosts.get(coord)
        free = self.free_by_node.get(node) if node is not None else None
        if free is not None:
            free.add(coord)
            contact[coord] = slicefit.point_contact(
                mesh, coord, self._blocked
            )
            owner[coord] = node
            touched.add(node)
        return touched

    def best_chip(self, node: str) -> Optional[TopologyCoord]:
        """The node's snuggest free chip under the legacy tie-break:
        max (contact, then lexicographically smallest coord) — the
        same key ``Extender._plan_chips``'s count==1 path uses."""
        free = self.free_by_node.get(node)
        if not free:
            return None
        cg = self.contact
        return max(free, key=lambda c: (cg[c], tuple(-v for v in c)))

    def best_contact(self, node: str) -> int:
        """Max contact over the node's free chips (-1 when none) — the
        quantity the legacy /prioritize count==1 path scores."""
        free = self.free_by_node.get(node)
        if not free:
            return -1
        cg = self.contact
        return max(cg[c] for c in free)


class SchedulingCycle:
    """The batch planner, owned by (and locked by) one Extender."""

    #: recent batch sizes / cycle walls kept for the /metrics summaries
    WINDOW = 512

    def __init__(self, extender, config) -> None:
        self._ext = extender
        self._max_pods = config.batch_max_pods
        self._interval = config.cycle_interval_seconds
        self._ttl = config.reservation_ttl_seconds
        # ISSUE 13 satellite: answer /filter (and /prioritize) FROM the
        # plan — the feasible set is the planned node alone, so the
        # webhook answer stops materializing the O(nodes) per-node
        # verdict list that was the 10k-node filter p99. Placement is
        # unchanged: the one offered node IS the max-score smallest-name
        # pick the full answer would have led the scheduler to.
        self._filter_from_plan = config.filter_from_plan
        # scheduling queue: pod key -> (PodInfo, enqueue seq, the
        # webhook's candidate node names or None for driver/informer
        # admissions). Insertion order is the arrival order; the cycle
        # re-sorts by priority. Per-pod names matter on real clusters:
        # kube-scheduler's /filter carries only the nodes that passed
        # its built-in predicates for THAT pod, so planning a queued
        # pod against another pod's candidate list would assume
        # placements onto nodes the pod may not even tolerate.
        self._queue: dict[str, tuple[PodInfo, int, Optional[tuple[str, ...]]]] = {}
        # pod key -> scheduling-clock FIRST-admit time, for the
        # pending-admit-age percentiles /statusz reports (the
        # starvation signal drf_order can hide) and the per-pod queue
        # wait the provenance layer records. The stamp survives
        # plan-and-retry cycles — a pod shed or refused for hours must
        # accumulate age, not reset per retry — and retires only when
        # the pod actually binds (on_bound), releases, or its plan
        # expires.
        self._enqueued_at: dict[str, float] = {}
        self._plans: dict[str, PodPlan] = {}
        self._seq = 0
        self._last_drain = float("-inf")  # clock time of last full drain
        # Persistent fast-path state (ISSUE 10): the overlay (per-node
        # free sets, contact dict, best-node heap) survives ACROSS
        # cycles and is patched from the snapshot cache's delta chain;
        # a full O(chips) rebuild happens only on structural change or
        # delta-log overflow. Owned by the decision lock like the rest.
        self._fast_state: Optional[dict[str, Any]] = None
        # counters (read by /metrics + /statusz under no extra lock —
        # the decision lock already serializes every writer)
        self.cycles = 0
        self.pods_planned = 0
        self.plan_hits = 0
        self.plan_misses = 0
        self.assumes = 0
        self.assume_undos = 0
        self.fast_patches = 0    # fast state advanced O(Δ) from deltas
        self.fast_rebuilds = 0   # fast state rebuilt O(chips)
        self.gang_batches = 0          # gang groups planned batched
        self.gang_batch_members = 0    # members planned by that arm
        self.batch_sizes: deque[int] = deque(maxlen=self.WINDOW)
        self.cycle_walls: deque[float] = deque(maxlen=self.WINDOW)
        self.cycle_wall_total = 0.0  # cumulative (the windows rotate)
        self.cycle_hist = Histogram("tpukube_cycle_wall_seconds",
                                    bucket_only=True)
        # queue-age histogram (ISSUE 17): the starvation signal the
        # percentile window on /statusz carries, exportable as _bucket
        # series so Prometheus can alert on it. Long-tail buckets: a
        # pod stuck for hours IS the signal, sub-second ages are noise.
        self.queue_age_hist = Histogram(
            "tpukube_cycle_queue_age_seconds", bucket_only=True,
            buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                     1800.0, 3600.0))

    # -- queue admission -----------------------------------------------------
    def enqueue(self, pod: PodInfo,
                names: Optional[tuple[str, ...]] = None) -> None:
        """Admit a pending pod (idempotent per pod key). ``names`` is
        the admitting webhook's candidate node list; None (the pod
        informer / sim batch driver) means every known node is a
        candidate and materialized webhook answers are not expected."""
        key = pod.key()
        # setdefault: re-deliveries AND refusal-retry re-admissions
        # keep the FIRST admit time — resetting per retry would hide
        # exactly the repeatedly-refused pod the age stat exists for
        self._enqueued_at.setdefault(key, self._ext.clock.monotonic())
        if key in self._queue:
            # keep the original seq (arrival order) but the fresh
            # object and candidate set
            self._queue[key] = (pod, self._queue[key][1], names)
            return
        self._seq += 1
        self._queue[key] = (pod, self._seq, names)

    def offer(self, pod: PodInfo) -> bool:
        """enqueue() unless the pod already has a LIVE plan — the
        informer feed re-delivers pending pods (MODIFIED events, every
        list resync), and replanning an ASSUMED allocation would commit
        its chips twice: the replan's commit fails, the fresh (broken)
        entry overwrites the assumed one in ``_plans``, and the
        original allocation is orphaned until the pod object dies.
        (Error entries are never live — _entry_current — so a shed or
        unschedulable pod re-enters the queue and re-runs the gate.)
        Invalidation on a genuinely changed pod belongs to
        filter_response, which undoes the assume first. Returns True
        when the pod actually entered the queue."""
        if self.plan_is_live(pod):
            return False
        self.enqueue(pod)
        return True

    def queue_depth(self) -> int:
        return len(self._queue)

    def planned_node(self, pod_key: str) -> Optional[str]:
        """The live plan's predicted node for ``pod_key`` (None when
        unplanned or found unschedulable)."""
        entry = self._plans.get(pod_key)
        return entry.node if entry is not None else None

    def _entry_current(self, entry: PodPlan) -> bool:
        """An ASSUMED entry stays servable regardless of later epochs —
        its allocation is committed, and the answer IS that commitment
        (re-planning would double-commit). A FILTER-ERROR answer is
        never served from cache: refusals may be time-dependent (the
        tenancy gate's SLO-burn shed subsides with no epoch moving),
        so each retry must re-run the gate — exactly what the
        recomputing legacy path did per webhook. Any other non-assumed
        entry (unschedulable node set, deferred preemption, a planned
        bind error — which take_for_bind consumes, so it cannot loop)
        is a cached pure function of cluster state: servable only
        while the epochs stand still."""
        if entry.assumed:
            return True
        if entry.error is not None:
            return False
        return entry.epoch_key == self._ext.snapshots.epoch_key()

    def plan_is_live(self, pod: PodInfo) -> bool:
        """True while this pod holds a servable plan (Extender.admit's
        informer-re-delivery dedup runs this BEFORE the tenancy gate,
        so an already-planned pod never journals a phantom refusal)."""
        entry = self._plans.get(pod.key())
        return (entry is not None and entry.uid == pod.uid
                and self._entry_current(entry))

    # -- webhook answers -----------------------------------------------------
    def filter_response(
        self,
        pod: PodInfo,
        raw_nodes: Optional[list[dict[str, Any]]],
        node_names: Optional[list[str]],
    ) -> Any:
        """The /filter decision in batch mode: ingest nodes, admit the
        pod, ensure it is planned (running a cycle if needed), and
        answer from the plan. Raises exactly what the legacy path
        raises (the caller maps errors to the wire error response)."""
        from tpukube.sched import kube

        ext = self._ext
        if raw_nodes is not None:
            names = ext._ingest_nodes(raw_nodes)
            by_name: Optional[dict[str, Any]] = dict(zip(names, raw_nodes))
        else:
            names = list(node_names or [])
            by_name = None
        mk = (kube.filter_result if raw_nodes is not None
              else kube.filter_result_names)

        ask = ext.device_request(pod)  # ExtenderError propagates (legacy)
        if ask is None:
            # not a TPU pod: everything feasible, nothing to plan
            return mk(raw_nodes if raw_nodes is not None else names, {})

        key = pod.key()
        entry = self._plans.get(key)
        fresh = (entry is not None and entry.uid == pod.uid
                 and entry.names == tuple(names)
                 and self._entry_current(entry))
        if fresh:
            self.plan_hits += 1
        else:
            if entry is not None and entry.assumed:
                # the scheduler is re-filtering a pod we already assumed
                # (changed node set / recreated pod): the old plan's
                # commitment must not shadow the new cycle
                self._undo_assume(entry)
            self._plans.pop(key, None)
            self.enqueue(pod, tuple(names))
            self.run_cycle(must_plan=key)
            entry = self._plans.get(key)
            if entry is None:
                # beyond the batch cap even after a cycle: legacy
                # answer (quiet: the handle() wrapper already times
                # this webhook — exactly one sample per webhook)
                self.plan_misses += 1
                with self._quiet():
                    feasible, failed = ext.filter(
                        pod, raw_nodes=raw_nodes, node_names=node_names
                    )
                return mk(feasible, failed)
            self.plan_misses += 1  # planned now, not answered from cache
        if entry.error is not None:
            dlog = ext.decisions
            if dlog is not None and dlog.wants(key):
                # the planned refusal the scheduler will see (the
                # tenancy gate recorded its own verdict at plan time)
                dlog.record(key, "refusal", kind="filter_error",
                            reason=entry.error)
            return mk([], {}, error=entry.error)
        # answer materialization: serving the wire lists from the plan
        # — a dict lookup plus O(feasible) list builds (vs the legacy
        # O(nodes) re-plan this path replaced)
        ph = ext.phase_hist
        a0 = time.perf_counter() if ph is not None else None
        feasible = entry.feasible
        if feasible is None:
            # planned without materialized answers — a driver-enqueued
            # pod whose webhooks were not expected, or any pod under
            # filter_from_plan (ISSUE 13: the O(nodes) answer build was
            # the 10k-node filter p99): the planned node alone is a
            # correct — if minimal — feasibility answer, and the
            # scheduler's pick then consumes the assumed allocation
            feasible = [entry.node] if entry.node is not None else []
        if by_name is not None:
            response = mk([by_name[n] for n in feasible if n in by_name],
                          dict(entry.failed))
        else:
            response = mk(list(feasible), dict(entry.failed))
        if a0 is not None:
            ph.labels(phase="answer").observe(time.perf_counter() - a0)
            if ext.trace is not None:
                ext.trace.span("cycle_answer", key, cycle=self.cycles)
        return response

    def prioritize_response(
        self, pod: PodInfo, names: list[str]
    ) -> Optional[dict[str, int]]:
        """Planned scores for exactly the requested names, or None when
        the plan cannot answer (the caller falls back to the legacy
        path and counts a miss)."""
        entry = self._plans.get(pod.key())
        if (entry is None or entry.uid != pod.uid
                or entry.error is not None
                or not self._entry_current(entry)):
            self.plan_misses += 1
            return None
        if not all(n in entry.scores for n in names):
            if self._filter_from_plan and entry.node is not None:
                # plan-served answers carry no materialized score map;
                # the planned node wins outright (it is the only node
                # the plan-served filter offered — extra names can only
                # come from another extender's merge and lose)
                from tpukube.sched.extender import MAX_SCORE

                self.plan_hits += 1
                return {n: (MAX_SCORE if n == entry.node else 0)
                        for n in names}
            self.plan_misses += 1
            return None
        self.plan_hits += 1
        return {n: entry.scores[n] for n in names}

    def take_for_bind(
        self, key: str, uid: str, node: str
    ) -> Optional[tuple[str, Any]]:
        """Consume the plan's /bind answer: ("ok", AllocResult) for an
        assumed allocation on the requested node, ("err", message) for
        a planned bind failure, None when the legacy bind path must run
        (no plan, deferred preemption, or the scheduler picked a
        different node — the assume is undone first)."""
        entry = self._plans.get(key)
        if entry is None or (uid and entry.uid and uid != entry.uid):
            return None
        if entry.assumed and entry.alloc is not None:
            self._plans.pop(key, None)
            if entry.node == node:
                self.plan_hits += 1
                # the pod is bound for real now: retire its pending-
                # webhook context exactly where the legacy bind does
                # (the admit-age stamp retires via on_bound)
                with self._ext._pending_lock:
                    self._ext._pending.pop(key, None)
                return ("ok", entry.alloc)
            # scheduler disagreed with the predicted node (another
            # extender's scores, a racing cycle): undo and re-plan
            self.plan_misses += 1
            self._undo_assume(entry)
            return None
        if (entry.bind_error is not None and entry.node == node
                and self._entry_current(entry)):
            self.plan_hits += 1
            self._plans.pop(key, None)
            return ("err", entry.bind_error)
        self.plan_misses += 1
        return None

    def note_pending(self, pod_key: str) -> None:
        """First-admit stamp for a pod refused at the admission gate
        WITHOUT entering the queue (Extender.admit's tenancy refusal):
        it is still pending — the informer feed retries it — and the
        queue-age starvation stats must count it from its first
        attempt. Retires like any stamp (bind/release)."""
        self._enqueued_at.setdefault(pod_key,
                                     self._ext.clock.monotonic())

    def on_bound(self, pod_key: str) -> None:
        """A bind actually committed (plan-served or legacy path):
        retire the first-admit stamp — the pod is no longer pending,
        so the starvation stats must stop counting it."""
        self._enqueued_at.pop(pod_key, None)

    def on_release(self, pod_key: str) -> None:
        """A recorded release arrived (pod deleted/evicted): a plan
        entry still assuming this pod must not keep counting it bound —
        the ledger release itself already happened in the decision. A
        still-QUEUED entry leaves too: planning a deleted pod would
        assume chips nobody will bind, and its admit time would keep
        inflating the queue-age starvation stats forever."""
        self._queue.pop(pod_key, None)
        self._enqueued_at.pop(pod_key, None)
        entry = self._plans.pop(pod_key, None)
        if entry is not None and entry.assumed:
            # the alloc is already released by the decision; only the
            # bookkeeping the assume added must unwind
            self._ext.binds_total -= 1
            self.assume_undos += 1

    # -- the cycle -----------------------------------------------------------
    def run_pending(self) -> int:
        """Drive cycles until the queue drains (the sim batch driver /
        pod-informer entry point; webhook-triggered planning goes
        through filter_response). Returns pods planned."""
        planned = 0
        while self._queue:
            planned += self.run_cycle(drain=True)
        return planned

    def run_cycle(self, must_plan: Optional[str] = None,
                  drain: bool = False) -> int:
        """Plan one batch. ``must_plan`` (a webhook's pod) is always
        included; the rest of the queue joins when ``drain`` is set or
        ``cycle_interval_seconds`` has elapsed since the last full
        drain — otherwise an arrival storm coalesces into fewer, bigger
        cycles instead of replanning per webhook. Each pod plans
        against ITS OWN candidate node list (the admitting webhook's,
        or every known node for driver/informer admissions) and only
        webhook-admitted pods pay for materialized filter/score
        answers."""
        ext = self._ext
        now = ext.clock.monotonic()
        self._expire_plans(now)
        full = (drain or self._interval <= 0
                or now - self._last_drain >= self._interval)
        batch: list[tuple[PodInfo, int, Optional[tuple[str, ...]]]] = []
        if full:
            if ext.tenants is not None:
                # multi-tenant plane: priority bands first (unchanged),
                # then progressive dominant-resource fairness within
                # each band — a neutral plane (one tenant) reproduces
                # the legacy order exactly (parity-tested)
                order = ext.tenants.drf_order(list(self._queue.values()))
            else:
                order = sorted(
                    self._queue.values(),
                    key=lambda e: (
                        -e[0].priority,
                        # gang-aware: members of one gang plan adjacently
                        # (their reservation assembles within one cycle),
                        # gangs ahead of strays within a priority band
                        (0, e[0].group.name) if e[0].group is not None
                        else (1, ""),
                        e[1],
                    ),
                )
            batch = order[: self._max_pods]
            self._last_drain = now
        if must_plan is not None and must_plan in self._queue and not any(
            p.key() == must_plan for p, _, _ in batch
        ):
            batch = batch[: max(0, self._max_pods - 1)]
            batch.append(self._queue[must_plan])
        if not batch:
            return 0
        t0 = time.perf_counter()
        # cycle phase profiling (ISSUE 12; None = off): pin wall
        # accumulates around the fast-state ensure; the snapshot
        # counters before the cycle attribute this pin as a delta
        # advance, a forced rebuild, or a cache hit in the provenance
        # records below
        ph = ext.phase_hist
        dlog = ext.decisions
        pin_s = 0.0
        ages: list[float] = []
        d0, r0 = ext.snapshots.delta_applies, ext.snapshots.rebuilds

        def _advance() -> str:
            # computed FRESH per record (never memoized): a batch whose
            # first pods plan before any snapshot work honestly reads
            # "cached", and the records after a delta advance / forced
            # rebuild — and the end-of-cycle span — attribute it
            if ext.snapshots.rebuilds > r0:
                return "rebuild"
            if ext.snapshots.delta_applies > d0:
                return "delta"
            return "cached"

        def _note_plan(key: str, entry: PodPlan, arm: str,
                       age: Optional[float]) -> None:
            if dlog is None or not dlog.wants(key):
                return
            dlog.record(
                key, "cycle_plan", cycle=self.cycles + 1, arm=arm,
                node=entry.node, assumed=entry.assumed,
                error=entry.error, bind_error=entry.bind_error,
                queue_age_s=(round(age, 6) if age is not None else None),
                snapshot=_advance(),
                epoch=(list(entry.epoch_key) if entry.epoch_key
                       else None),
            )

        def _note_stranded(p: PodInfo, entry: PodPlan) -> None:
            # stranded-demand forensics (ISSUE 17): every plan that
            # produced no node — a refusal error or an unschedulable
            # verdict (feasible computed, empty) — gets root-caused by
            # the capacity recorder. Assumed plans and plan-served
            # binds are successes; bind errors are transport, not
            # capacity.
            cap = ext.capacity
            if (cap is not None and entry.node is None
                    and not entry.assumed
                    and (entry.error is not None
                         or entry.feasible is not None)):
                cap.note_failed_plan(p, entry.error)

        def _age_of(key: str) -> Optional[float]:
            # READ, never pop: the first-admit stamp outlives the plan
            # so a refused-and-retried pod keeps accumulating age
            # (on_bound/on_release/_expire_plans retire it)
            qt = self._enqueued_at.get(key)
            if qt is None:
                return None
            age = max(0.0, now - qt)
            ages.append(age)
            self.queue_age_hist.observe(age)
            return age

        # ONE shared tuple for driver/informer admissions: every such
        # PodPlan stores `names` verbatim, and at 10k nodes a per-entry
        # copy is ~80KB — tuple(t) on an existing tuple is identity, so
        # sharing here dedupes every downstream tuple(names)
        default_names: Optional[tuple[str, ...]] = None
        i = 0
        while i < len(batch):
            pod, seq, pod_names = batch[i]
            if pod.group is not None and pod_names is None:
                # batched gang planning (ISSUE 10): the queue order put
                # this gang's driver-admitted members adjacent — plan
                # the whole run through ONE reservation sweep and ONE
                # availability pass instead of the per-member general
                # path (which re-derives both over every node)
                gkey = (pod.namespace, pod.group.name)
                j = i
                members: list[tuple[PodInfo, int]] = []
                while j < len(batch):
                    p2, s2, n2 = batch[j]
                    if (n2 is None and p2.group is not None
                            and (p2.namespace, p2.group.name) == gkey):
                        members.append((p2, s2))
                        j += 1
                    else:
                        break
                if default_names is None:
                    default_names = tuple(ext.state.node_names())
                for (p2, _), entry in zip(members, self._plan_gang_batch(
                        members, default_names)):
                    key2 = p2.key()
                    self._queue.pop(key2, None)
                    self._plans[key2] = entry
                    self.pods_planned += 1
                    _note_plan(key2, entry, "gang_batch",
                               _age_of(key2))
                    _note_stranded(p2, entry)
                i = j
                continue
            key = pod.key()
            self._queue.pop(key, None)
            age = _age_of(key)
            if pod_names is not None:
                names = list(pod_names)
                # a webhook will read the answers — unless plan-served
                # filter answers are on, in which case the planned node
                # alone answers and the O(nodes) materialization is the
                # cost this mode exists to kill
                needs_answers = not self._filter_from_plan
            else:
                if default_names is None:
                    default_names = tuple(ext.state.node_names())
                names = default_names
                needs_answers = False
            if self._fast_eligible(pod):
                # the same janitor the legacy filter runs per webhook;
                # BEFORE the staleness check — a TTL/fault rollback
                # bumps the epoch and must advance/rebuild the overlay
                ext.gang.sweep()
                if ph is not None:
                    p0 = time.perf_counter()
                    fast_state = self._ensure_fast_state()
                    pin_s += time.perf_counter() - p0
                else:
                    fast_state = self._ensure_fast_state()
                entry = self._plan_fast(pod, seq, names, fast_state,
                                        needs_answers)
                if entry.assumed:
                    # commit moved the ledger epoch exactly as planned
                    # (the overlay was patched in-place by _plan_fast)
                    fast_state["key"] = ext.snapshots.epoch_key()
                arm = "fast"
            else:
                entry = self._plan_general(pod, seq, names)
                arm = "general"
            entry.epoch_key = ext.snapshots.epoch_key()
            self._plans[key] = entry
            self.pods_planned += 1
            _note_plan(key, entry, arm, age)
            _note_stranded(pod, entry)
            i += 1
        self.cycles += 1
        self.batch_sizes.append(len(batch))
        wall = time.perf_counter() - t0
        self.cycle_walls.append(wall)
        self.cycle_wall_total += wall
        self.cycle_hist.observe(wall)
        # flight-recorder cadence (ISSUE 17): batch-driven drives may
        # never touch the webhook tail's hook, so the cycle end is the
        # sampling seam — one clock read when the interval has not
        # elapsed
        if ext.capacity is not None:
            ext.capacity.maybe_sample()
        if ph is not None:
            # additive phases: queue wait (the batch's longest), the
            # snapshot/fast-state pin, and the planning remainder
            if ages:
                ph.labels(phase="queue").observe(max(ages))
            ph.labels(phase="pin").observe(pin_s)
            ph.labels(phase="plan").observe(max(0.0, wall - pin_s))
            if ext.trace is not None:
                # timeline spans (cluster track): Chrome-trace exports
                # show the batch structure cycle by cycle
                ext.trace.span("cycle_pin", "", cycle=self.cycles,
                               wall_s=round(pin_s, 6),
                               snapshot=_advance())
                ext.trace.span("cycle_plan", "", cycle=self.cycles,
                               pods=len(batch), wall_s=round(wall, 6))
        return len(batch)

    def _pin_snapshot(self):
        """The ONE place this module reads the epoch cache — the
        snapshot-discipline lint pins every other SnapshotCache read or
        sweep construction in cycle.py to this seam."""
        return self._ext.snapshots.current()

    @contextmanager
    def _quiet(self):
        """Suppress webhook-latency observation around plan-time
        internal calls: with batching on, each REAL webhook records
        exactly one latency sample (handle() times the plan/lookup),
        never the phantom prioritize/bind samples the planner's
        internal calls would otherwise add. Single-threaded by
        construction — every caller holds the decision lock."""
        ext = self._ext
        prev = ext._suppress_latency
        ext._suppress_latency = True
        try:
            yield
        finally:
            ext._suppress_latency = prev

    # -- the general path (gang / vTPU / multi-chip) -------------------------
    def _plan_general(self, pod: PodInfo, seq: int,
                      names: list[str]) -> PodPlan:
        """Plan one pod by running the SAME per-pod code the legacy
        webhooks run, in webhook order (filter -> prioritize -> pick ->
        bind), recording each answer. Bit-identity with the legacy path
        is structural: it IS the legacy path, executed at plan time."""
        from tpukube.sched.extender import ExtenderError

        ext = self._ext
        entry = PodPlan(pod, tuple(names), ext.clock.monotonic(), seq)
        with self._quiet():
            # quiet: plan-time internal calls must not feed the webhook
            # latency histograms — each REAL webhook records exactly
            # one sample (the filter wrapper times the whole plan; the
            # prioritize/bind webhooks time their plan lookups)
            try:
                feasible, failed = ext.filter(pod, node_names=list(names))
            except (ExtenderError, GangError, StateError,
                    codec.CodecError) as e:
                entry.error = str(e)
                return entry
            entry.feasible = [
                n if isinstance(n, str) else n["metadata"]["name"]
                for n in feasible
            ]
            entry.failed = dict(failed)
            if not entry.feasible:
                return entry
            try:
                entry.scores = ext.prioritize(
                    pod, node_names=list(entry.feasible)
                )
            except (ExtenderError, GangError, StateError,
                    codec.CodecError) as e:
                log.warning("plan prioritize failed: %s", e)
                entry.scores = {n: 0 for n in entry.feasible}
            entry.node = max(sorted(entry.scores),
                             key=lambda h: entry.scores[h])
            res = None
            if pod.group is not None:
                res = ext.gang.reservation(pod.namespace, pod.group.name)
            if res is not None and (
                ext.gang.peek_pending_victims(res)
                or ext.gang.terminating_victims_of(res)
            ):
                # two-phase preemption: its execution (and the PDB
                # precheck guarding it) belongs to the real /bind
                # webhook — defer
                return entry
            try:
                entry.alloc = ext.bind(pod.name, pod.namespace, pod.uid,
                                       entry.node)
                entry.assumed = True
                self.assumes += 1
                # bind() consumed the pending-webhook context; re-arm
                # it so a node-mismatch fallback (or duplicate filter)
                # can still re-plan through the legacy path
                ext._remember(pod)
            except (ExtenderError, GangError, StateError,
                    codec.CodecError) as e:
                entry.bind_error = str(e)
            return entry

    # -- batched gang planning (ISSUE 10) ------------------------------------
    def _plan_gang_batch(
        self, members: list[tuple[PodInfo, int]], names: list[str]
    ) -> list[PodPlan]:
        """Plan one gang's queued (driver-admitted) members as a batch:
        the reservation's shape candidates run through the vectorized
        slicefit sweep ONCE (ensure_reservation, exactly as the legacy
        first member's filter), then every member picks its node from
        ONE ``node_availability`` pass kept current by O(1) decrements
        — instead of the per-member general path, which re-runs filter
        + prioritize over every node per member (O(members × nodes)).

        Placement parity with the legacy path is preserved move for
        move: the pick is argmax of the same ``score_from`` quantity
        with the same smallest-name tie-break, candidates are the same
        feasibility set (nodes holding ≥ chips_per_pod unassigned
        reserved chips), binds run the REAL ``Extender.bind`` (chip
        choice, quorum commit, ledger). Anything off the clean path —
        preemption (pending or terminating victims), non-whole-chip
        requests, config errors, overflow replicas — falls back to the
        per-member general path, which IS the legacy code."""
        from tpukube.sched.extender import ExtenderError

        ext = self._ext
        # the janitor every legacy gang filter runs (TTL/fault rollback
        # before reservation reads); per-member re-sweeps inside
        # ensure_reservation are cheap once the reservation exists
        ext.gang.sweep()
        dlog = ext.decisions
        entries: list[PodPlan] = []
        counts: Optional[dict[str, tuple[int, int]]] = None
        general = False  # sticky: preemption routed this gang legacy
        batched = 0
        with self._quiet():
            for pod, seq in members:
                if general:
                    entries.append(self._general(pod, seq, names))
                    continue
                entry = PodPlan(pod, tuple(names), ext.clock.monotonic(),
                                seq)
                try:
                    ask = ext.device_request(pod)
                except (ExtenderError, codec.CodecError) as e:
                    entry.error = str(e)
                    entry.epoch_key = ext.snapshots.epoch_key()
                    entries.append(entry)
                    continue
                if ask is None or ask[0] != RESOURCE_TPU:
                    # not a whole-chip gang member (the legacy filter
                    # raises / treats it specially): general path
                    entries.append(self._general(pod, seq, names))
                    continue
                count = ask[1]
                if ext.tenants is not None:
                    refusal = ext.tenants.admit(pod, RESOURCE_TPU, count)
                    if refusal is not None:
                        entry.error = refusal
                        entry.epoch_key = ext.snapshots.epoch_key()
                        entries.append(entry)
                        continue
                ext._remember(pod)
                try:
                    res = ext.gang.ensure_reservation(pod, count)
                except NoSliceError:
                    # preemption territory: the general path plans it
                    # (two-phase victims, deferred first bind) — and
                    # stays authoritative for the rest of the gang
                    general = True
                    entries.append(self._general(pod, seq, names))
                    continue
                except (GangError, StateError) as e:
                    entry.error = str(e)
                    entry.epoch_key = ext.snapshots.epoch_key()
                    entries.append(entry)
                    continue
                if dlog is not None and dlog.wants(pod.key()):
                    # the gang rendezvous leg of the provenance chain
                    # (the legacy filter records it inline; this arm
                    # reserves directly)
                    dlog.record(
                        pod.key(), "gang_reserve",
                        gang=f"{pod.namespace}/{pod.group.name}",
                        chips=res.total_chips(),
                        committed=res.committed,
                    )
                if (ext.gang.peek_pending_victims(res)
                        or ext.gang.terminating_victims_of(res)):
                    general = True
                    entries.append(self._general(pod, seq, names))
                    continue
                if not ext.gang.assignable(res, count):
                    # overflow replica of a full gang: normal placement
                    entries.append(self._general(pod, seq, names))
                    counts = None  # a normal bind may touch gang nodes
                    continue
                if counts is None:
                    counts = ext.gang.node_availability(res)
                cands = sorted(
                    n for n, (a, _) in counts.items() if a >= count
                )
                if not cands:
                    # no node holds enough unassigned reserved chips:
                    # the legacy filter would answer "infeasible
                    # everywhere" — an unschedulable entry (the driver
                    # requeues; a webhook gets empty feasibility)
                    entry.feasible = []
                    entry.epoch_key = ext.snapshots.epoch_key()
                    entries.append(entry)
                    continue
                # argmax of score_from with the legacy smallest-name
                # tie-break (max over an ascending-sorted list returns
                # the first maximal element)
                entry.node = max(
                    cands,
                    key=lambda n: GangManager.score_from(counts, n),
                )
                try:
                    entry.alloc = ext.bind(pod.name, pod.namespace,
                                           pod.uid, entry.node)
                    entry.assumed = True
                    self.assumes += 1
                    batched += 1
                    ext._remember(pod)
                    avail, total = counts[entry.node]
                    counts[entry.node] = (avail - count, total)
                except (ExtenderError, GangError, StateError,
                        codec.CodecError) as e:
                    entry.bind_error = str(e)
                    counts = None  # uncertain state: recompute next
                entry.epoch_key = ext.snapshots.epoch_key()
                entries.append(entry)
        if batched:
            self.gang_batches += 1
            self.gang_batch_members += batched
        return entries

    def _general(self, pod: PodInfo, seq: int,
                 names: list[str]) -> PodPlan:
        """_plan_general + the epoch-key stamp run_cycle's normal path
        applies (gang-arm fallbacks must carry it identically)."""
        entry = self._plan_general(pod, seq, names)
        entry.epoch_key = self._ext.snapshots.epoch_key()
        return entry

    # -- the fast path (single whole-chip pods, topology scoring) ------------
    def _fast_eligible(self, pod: PodInfo) -> bool:
        from tpukube.sched.extender import ExtenderError

        if pod.group is not None:
            return False
        if self._ext._config.score_mode != "topology":
            return False
        try:
            ask = self._ext.device_request(pod)
        except ExtenderError:
            return False  # the general path reports the schema error
        return ask is not None and ask[0] == RESOURCE_TPU and ask[1] == 1

    def _ensure_fast_state(self) -> dict[str, Any]:
        """The persistent fast-path state, advanced to the current
        epochs: patched O(Δ) from the snapshot cache's delta chain when
        it covers the gap, rebuilt O(chips) otherwise (cold start,
        structural change, log overflow). At 10k nodes the per-cycle
        rebuild — contact-grid tolist + every node's free set — was the
        dominant constant the O(log nodes)/pod placement path left."""
        ext = self._ext
        key = ext.snapshots.epoch_key()
        fs = self._fast_state
        if fs is not None and fs["key"] == key:
            return fs
        if fs is not None:
            deltas = ext.snapshots.deltas_between(fs["key"], key)
            if deltas is not None and not any(d.full for d in deltas):
                snap = self._pin_snapshot()
                if self._patch_fast_state(fs, snap, deltas):
                    fs["key"] = key
                    fs["snap"] = snap
                    self.fast_patches += 1
                    return fs
        snap = self._pin_snapshot()
        fs = self._build_fast_state(snap)
        self._fast_state = fs
        self.fast_rebuilds += 1
        return fs

    def _patch_fast_state(self, fs: dict[str, Any], snap,
                          deltas: list) -> bool:
        """Advance the overlay in place by the same delta chain the
        snapshot cache applied: explicit occupied add/remove coords
        from the ledger stream; reserved-mask moves as the per-slice
        set difference between the previously pinned snapshot and the
        fresh one (gang deltas name the touched slices; the masks are
        small). False = a slice the overlay never built appeared —
        caller rebuilds. Net-effect application is order-insensitive:
        every mutator fires block/unblock effects only on a membership
        transition of the occ ∪ resv union."""
        overlays: dict[str, _SliceOverlay] = fs["overlays"]
        old_snap = fs["snap"]
        touched: set[str] = set()
        gang_slices: set[str] = set()
        for d in deltas:
            if d.kind == "gang":
                gang_slices.update(d.slices)
                continue
            if d.slice_id is None:
                continue  # empty ledger bump (release on a gone node)
            ov = overlays.get(d.slice_id)
            if ov is None:
                return False
            for c in d.occupied_add:
                touched |= ov.set_occupied(c)
            for c in d.occupied_remove:
                touched |= ov.clear_occupied(c)
        for sid in gang_slices:
            ov = overlays.get(sid)
            old = old_snap.slices.get(sid)
            new = snap.slices.get(sid)
            if ov is None or old is None or new is None:
                return False
            for c in new.reserved - old.reserved:
                touched |= ov.set_reserved(c)
            for c in old.reserved - new.reserved:
                touched |= ov.clear_reserved(c)
        heap = fs["heap"]
        node_best = fs["node_best"]
        for name in touched:
            sid = fs["node_slice"].get(name)
            if sid is None:
                continue
            best = overlays[sid].best_contact(name)
            if node_best.get(name, -1) != best:
                node_best[name] = best
                if best >= 0:
                    heapq.heappush(heap, (-best, name, best))
        # lazy validation leaves stale heap entries behind; compact
        # before they dominate (a churn drive pushes one entry per
        # touched node per wave)
        if len(heap) > max(1024, 4 * len(node_best)):
            heap[:] = [(-b, n, b) for n, b in node_best.items()
                       if b >= 0]
            heapq.heapify(heap)
        return True

    def _build_fast_state(self, snap) -> dict[str, Any]:
        """Shared structures for the fast path, derived from the pinned
        snapshot over EVERY known node (per-pod candidate lists select
        from it at query time): slice overlays (free-chip contact dicts
        + free sets + the mutable blocked-union membership), the
        vTPU-mode set, and the best-node heap the driver placement loop
        pops from — O(nodes + chips) to build, O(log nodes) per
        placement, O(Δ) to carry across cycles (_patch_fast_state)."""
        ext = self._ext
        overlays: dict[str, _SliceOverlay] = {}
        vtpu_nodes: set[str] = set()
        node_slice: dict[str, str] = {}
        node_best: dict[str, int] = {}
        heap: list[tuple[int, str, int]] = []
        reserved = snap.reserved_by_slice()
        grids: dict[str, list] = {}
        for sid in snap.slice_ids():
            ss = snap.slice(sid)
            # the pinned snapshot's vectorized contact grid, read once
            # into plain nested lists (fast scalar access) — the shared
            # cached ndarray itself is never mutated
            grids[sid] = ss.blocked_sweep().contact_grid().tolist()
            overlays[sid] = _SliceOverlay(
                mesh=ss.mesh, contact={}, free_by_node={}, owner={},
                occ=set(ss.occupied), resv=set(ss.reserved),
                hosts=ext.state.hosts_by_coord(sid),
            )
        for name in ext.state.node_names():
            view = ext.state.node(name)
            if view is None:
                continue
            if view.shares_per_chip > 1:
                vtpu_nodes.add(name)
                continue
            sid = view.info.slice_id
            ov = overlays.get(sid)
            if ov is None:
                continue  # slice raced away mid-cycle: unknown at query
            node_slice[name] = sid
            mask = reserved.get(sid, frozenset())
            grid = grids[sid]
            free = {c.coord for c in view.free_chips()
                    if c.coord not in mask}
            ov.free_by_node[name] = free
            best = -1
            for c in free:
                ov.owner[c] = name
                contact = grid[c[0]][c[1]][c[2]]
                ov.contact[c] = contact
                if contact > best:
                    best = contact
            node_best[name] = best
            if best >= 0:
                heap.append((-best, name, best))
        heapq.heapify(heap)
        return {
            "key": ext.snapshots.epoch_key(),
            "snap": snap,
            "overlays": overlays,
            "vtpu": vtpu_nodes,
            "node_slice": node_slice,
            "node_best": node_best,
            "heap": heap,
        }

    def _plan_fast(self, pod: PodInfo, seq: int, names: list[str],
                   fs: dict[str, Any], needs_answers: bool) -> PodPlan:
        """One single-chip pod against the cycle overlay: O(nodes) to
        materialize webhook answers (skipped for driver-enqueued pods
        whose webhooks never ask), O(1) to place and assume."""
        from tpukube.sched.extender import MAX_SCORE, ExtenderError

        ext = self._ext
        entry = PodPlan(pod, tuple(names), ext.clock.monotonic(), seq)
        if ext.tenants is not None:
            # the same tenancy admission gate the general path hits
            # inside ext.filter — the fast path answers webhooks too,
            # so a quota breach or SLO shed must refuse identically
            refusal = ext.tenants.admit(pod, RESOURCE_TPU, 1)
            if refusal is not None:
                entry.error = refusal
                return entry
        ext._remember(pod)
        overlays: dict[str, _SliceOverlay] = fs["overlays"]
        node_slice: dict[str, str] = fs["node_slice"]

        best_node: Optional[str] = None
        if needs_answers:
            best_score = -1
            feasible: list[str] = []
            failed: dict[str, str] = {}
            scores: dict[str, int] = {}
            for name in names:
                sid = node_slice.get(name)
                if sid is None:
                    failed[name] = (
                        "node is vTPU mode, pod wants whole chips"
                        if name in fs["vtpu"]
                        else "no tpukube node-topology annotation"
                    )
                    continue
                ov = overlays[sid]
                free = len(ov.free_by_node.get(name, ()))
                if free < 1:
                    failed[name] = (
                        f"wants 1 chips, node has {free} free "
                        f"(gang reservations excluded)"
                    )
                    continue
                feasible.append(name)
                contact = ov.best_contact(name)
                score = (round(MAX_SCORE * contact / 6)
                         if contact >= 0 else 0)
                scores[name] = score
                if score > best_score or (
                    score == best_score
                    and (best_node is None or name < best_node)
                ):
                    best_score, best_node = score, name
            entry.feasible = feasible
            entry.failed = failed
            entry.scores = scores
        else:
            # driver path: pop the argmax node off the lazily-validated
            # heap — identical choice to the materialized loop (best
            # contact maps 1:1 to score, ties break on smallest name),
            # at O(log nodes) instead of O(nodes x chips) per pod
            heap = fs["heap"]
            node_best = fs["node_best"]
            while heap:
                _, name, best = heapq.heappop(heap)
                if node_best.get(name, -1) == best and best >= 0:
                    # push the entry straight back: if the placement
                    # below leaves this node's best unchanged, the node
                    # must stay in the heap (the refresh only pushes on
                    # CHANGE); a duplicate is harmless under lazy
                    # validation
                    heapq.heappush(heap, (-best, name, best))
                    best_node = name
                    break
        if best_node is None:
            if needs_answers:
                return entry
            entry.error = "unschedulable: no feasible node in the batch plan"
            return entry
        entry.node = best_node
        ov = overlays[node_slice[best_node]]
        coord = ov.best_chip(best_node)
        view = ext.state.node(best_node)
        if coord is None or view is None:
            entry.bind_error = (
                f"{pod.key()}: node {best_node} can no longer fit 1 x "
                f"{RESOURCE_TPU}"
            )
            return entry
        env: dict[str, str] = {}
        if ext.tenants is not None:
            from tpukube.device.tpu import ENV_KUBE_TENANT

            # same tenant attribution the legacy bind writes — the
            # assumed allocation's annotation must match it exactly
            env[ENV_KUBE_TENANT] = ext.tenants.tenant_of(pod)
        try:
            did = make_device_id(view.index_at(coord))
            alloc = AllocResult(
                pod_key=pod.key(),
                node_name=best_node,
                device_ids=[did],
                coords=[coord],
                env=env,
                priority=pod.priority,
                uid=pod.uid or "",
            )
            ext.state.commit(alloc)
        except (StateError, ExtenderError) as e:
            entry.bind_error = str(e)
            return entry
        ext.binds_total += 1
        entry.alloc = alloc
        entry.assumed = True
        self.assumes += 1
        # O(1) overlay update + best-score refresh for the few nodes
        # the placement touched (heap entries are validated lazily).
        # set_occupied keeps the persistent overlay's blocked union in
        # lockstep with the ledger commit above, so the delta chain
        # patching the NEXT cycle starts from a consistent base.
        heap = fs["heap"]
        node_best = fs["node_best"]
        for name in ov.set_occupied(coord):
            best = ov.best_contact(name)
            if node_best.get(name, -1) != best:
                node_best[name] = best
                if best >= 0:
                    heapq.heappush(heap, (-best, name, best))
        return entry

    # -- hygiene -------------------------------------------------------------
    def _undo_assume(self, entry: PodPlan) -> None:
        """Release an assumed-but-unbound allocation (node mismatch,
        re-filter, expiry): the ledger/gang release the legacy effector
        undo performs, minus the wire response."""
        ext = self._ext
        key = entry.pod.key()
        if ext.state.release(key) is not None:
            ext.gang.on_release(key)
            ext.binds_total -= 1
            self.assume_undos += 1
            log.warning("assumed allocation for %s undone (re-plan)", key)
        if ext.decisions is not None and ext.decisions.wants(key):
            ext.decisions.record(key, "assume_undo",
                                 node=entry.node)
        entry.assumed = False
        entry.alloc = None

    def _expire_plans(self, now: float) -> None:
        """Plans whose /bind never came expire on the reservation-TTL
        horizon — the same janitor contract the gang sweep applies to
        its reservations. Assumed allocations are released; non-assumed
        entries (unschedulable / failed answers) are dropped too — a
        daemon fed a stream of never-binding pods with unique names
        must not grow ``_plans`` without bound."""
        for key, entry in list(self._plans.items()):
            if now - entry.planned_at <= self._ttl:
                continue
            if entry.assumed:
                log.warning(
                    "assumed allocation for %s never bound within %.0fs; "
                    "releasing", key, self._ttl,
                )
                self._undo_assume(entry)
            elif (self._ext.decisions is not None
                    and self._ext.decisions.wants(key)):
                self._ext.decisions.record(key, "plan_expired")
            self._plans.pop(key, None)
            # the TTL horizon also retires the admit stamp: a pod whose
            # plan expired unbound restarts its pending-age clock if it
            # ever comes back
            self._enqueued_at.pop(key, None)

    # -- observability -------------------------------------------------------
    def is_pending(self, pod_key: str) -> bool:
        """True while ``pod_key`` has an un-retired first-admit stamp —
        i.e. it was admitted and has not bound, released, or TTL'd out.
        The capacity recorder's stranded ledger uses this to expire
        entries whose demand left the system (ISSUE 17)."""
        return pod_key in self._enqueued_at

    def pending_oldest_age(self, now: float) -> Optional[float]:
        """Oldest pending-admit age at clock time ``now`` (None when
        nothing is pending). Same bounded-retry snapshot as stats():
        admission threads insert while the recorder reads."""
        stamps: list[float] = []
        for _ in range(5):
            try:
                stamps = list(self._enqueued_at.values())
                break
            except RuntimeError:  # dict mutated mid-iteration
                continue
        if not stamps:
            return None
        return max(0.0, now - min(stamps))

    def stats(self) -> dict[str, Any]:
        """The /statusz "cycle" section."""
        from tpukube.obs.registry import quantile

        lookups = self.plan_hits + self.plan_misses
        walls = list(self.cycle_walls)
        # pending-admit AGES, not just depth: drf_order can starve a
        # unit indefinitely while depth looks healthy — the oldest
        # admitted-but-never-bound age is the starvation signal, and
        # it survives refusal retries (first-admit stamps retire only
        # at bind/release/TTL). Snapshot with a bounded retry: /statusz
        # scrapes read while admission threads insert (the same guard
        # DecisionLog.events uses).
        now = self._ext.clock.monotonic()
        stamps: list[float] = []
        for _ in range(5):
            try:
                stamps = list(self._enqueued_at.values())
                break
            except RuntimeError:  # dict mutated mid-iteration
                continue
        ages = sorted(max(0.0, now - t) for t in stamps)
        return {
            "enabled": True,
            "cycles": self.cycles,
            "pods_planned": self.pods_planned,
            "queue_depth": len(self._queue),
            "queue_oldest_age_s": (round(ages[-1], 3) if ages else None),
            "queue_age_p50_s": (round(quantile(ages, 0.5), 3)
                                if ages else None),
            "queue_age_p99_s": (round(quantile(ages, 0.99), 3)
                                if ages else None),
            "plans_live": len(self._plans),
            "assumes": self.assumes,
            "assume_undos": self.assume_undos,
            # ISSUE 10: persistent fast-state maintenance + batched
            # gang planning — patches should dwarf rebuilds at scale
            "fast_patches": self.fast_patches,
            "fast_rebuilds": self.fast_rebuilds,
            "gang_batches": self.gang_batches,
            "gang_batch_members": self.gang_batch_members,
            "plan_hits": self.plan_hits,
            "plan_misses": self.plan_misses,
            "plan_hit_ratio": (round(self.plan_hits / lookups, 4)
                               if lookups else None),
            "last_batch_size": (self.batch_sizes[-1]
                                if self.batch_sizes else 0),
            "last_cycle_wall_s": (round(walls[-1], 6) if walls else None),
            # normalized planning cost — the perf-floor smoke's number
            # (cycle walls alone mix 1-pod and 1024-pod batches)
            "plan_ms_per_pod": (
                round(1000 * self.cycle_wall_total / self.pods_planned, 4)
                if self.pods_planned else None
            ),
            "batch_max_pods": self._max_pods,
            "cycle_interval_seconds": self._interval,
        }

"""Slice-partitioned control plane (ISSUE 13 tentpole).

BENCH_r06 showed the single planner process as the throughput ceiling:
one ``ClusterState``/``GangManager`` owns the whole fleet, so scenario
12 tops out around 1,650 pods/s at 10,240 nodes — the same
single-extender-webhook shape PAPER.md §1 identifies as KubeGPU's
scaling limit. ICI slices are already the natural partition unit
(snapshots, ``SnapshotDelta`` chains, fragmentation gauges, locks, and
the tenancy ledger are all per-slice), so this module partitions the
control plane BY SLICE:

  * :class:`PlannerReplica` — one shard: a full
    :class:`~tpukube.sched.extender.Extender` owning a DISJOINT slice
    set, with its own ledger, gang manager, snapshot/delta chain,
    scheduling queue, and journal segment (``<journal_path>.r<i>``).
  * :class:`ShardRouter` — the thin routing layer in front of the N
    replicas. It speaks the same decision surface as a single Extender
    (``handle``/``admit``/``plan_pending``/``planned_node``/...), so
    the sim harness, the apiserver loops, and the chaos checkers run
    against either unchanged. Nodes route by the slice id in their
    topology annotation; pods route by slice affinity (their gang's
    home replica, their allocation's owner, or a stable hash with
    capacity spillover); binds route by the target node's owner.

Parity gate: with ``planner_replicas == 1`` every router entry point
delegates VERBATIM to the sole replica's Extender — the N=1 sharded
path is byte-identical to the unsharded planner by construction
(tests/test_shard.py proves it end to end).

Two-phase rendezvous for DCN-spanning gangs
-------------------------------------------

A gang confined to one replica's slices reserves and commits locally,
exactly as today. A gang that fits NO single replica — and opted in to
DCN spanning (``PodGroup.allow_dcn``) — goes through a rendezvous
coordinated by the router on behalf of the initiating (home) replica,
built on the existing ``gang.py`` reservation/epoch machinery:

  1. PLAN: the router asks every alive replica's epoch-cached snapshot
     for its largest contiguous free boxes (one box per slice, each a
     multiple of chips_per_pod — the same greedy
     ``_plan_dcn_split`` shape, spread across replicas).
  2. PREPARE: each participant replica reserves its part through
     ``GangManager.reserve_exact_split`` under its own locks, with a
     LOCAL group whose ``min_member`` is the part's member count — so
     the part commits by its own quorum and sweeps by its own TTL.
     A duplicate prepare is idempotent (``reserve_exact_split``
     returns the existing reservation for the key), and a prepare that
     loses a race (box re-occupied) raises without touching anything.
  3. COMMIT-OR-ABORT: all prepares landed → the rendezvous is
     recorded and member pods fan out to participants with unassigned
     room; any prepare failed → every prepared part is dropped
     (``drop_reservation`` — no members yet, nothing to evict). After
     that, the rendezvous janitor (:meth:`ShardRouter.sweep`) keeps
     the all-or-nothing contract: if ANY uncommitted part disappears —
     TTL expiry, chip/link fault rollback, a replica killed or
     partitioned mid-commit — the surviving parts are dissolved
     (members evicted through the shared eviction bus), exactly the
     death a single-planner gang rollback dies.

The PR 6 reservation-leak prover and the snapshot-audit sentinel keep
holding: every reservation mutation goes through the proven
``gang.py`` seams, and each replica audits its own snapshot chain.

Production shape: this in-process router serves the sim/bench plane;
a real deployment runs one extender process per replica (each
configured with its slice set and journal segment) behind the same
routing contract, with the router as the stateless webhook front —
its maps are re-derivable from node annotations and the replicas'
reservations (see ``rebuild_from_pods``).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import zlib
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace as dc_replace
from typing import Any, Optional

from tpukube import trace as trace_mod
from tpukube.core import codec
from tpukube.core.config import TpuKubeConfig
from tpukube.core.types import AllocResult, PodGroup, PodInfo, TopologyCoord
from tpukube.sched import kube, slicefit, wirecodec
from tpukube.sched.extender import Extender, ExtenderError
from tpukube.sched.gang import GangError
from tpukube.sched.state import StateError

log = logging.getLogger("tpukube.shard")


class ShardError(RuntimeError):
    pass


class ReplicaUnavailable(ShardError):
    """A replica transport call could not reach its daemon (connection
    refused/reset, timeout). The router treats the replica as dead —
    the same semantics as ``crash_replica`` — and routes around it."""


class _ListApi:
    """Minimal apiserver read surface over captured node/pod object
    lists — the reconcile source ``restart_replica`` hands the journal
    recovery (a replica has no live apiserver of its own; the router's
    feed is the same truth ``rebuild_from_pods`` would consume)."""

    def __init__(self, nodes: list[dict], pods: list[dict]):
        self._nodes = list(nodes)
        self._pods = list(pods)

    def list_nodes(self) -> list[dict]:
        return list(self._nodes)

    def list_pods(self, node_name=None) -> list[dict]:
        del node_name
        return list(self._pods)


# -- replica-side helpers ----------------------------------------------------
#
# The decision surface one planner replica serves, shared VERBATIM by
# the in-process transport (direct calls) and the subprocess worker's
# HTTP routes (sched/shardworker.py): whatever transport carries the
# request, the replica-side computation is this one code path.

def replica_gauges(extender: Extender) -> dict[str, dict[str, Any]]:
    """Per-slice capacity gauges off the replica's EPOCH-CACHED
    snapshot — O(slices), no ledger walk, no sweep probe. The router's
    rendezvous PLAN phase and its routing order feed on these instead
    of serializing full fit probes over the wire (``largest_free_box``
    is cached on the snapshot; it can only OVER-estimate the blocked
    sweep's contiguity, so a gauge-based pre-filter never skips a
    replica the full probe would have accepted)."""
    snap = extender.snapshots.current()
    out: dict[str, dict[str, Any]] = {}
    for sid in snap.slice_ids():
        ss = snap.slice(sid)
        out[sid] = {
            "largest_free_box": ss.largest_free_box(),
            "free_chips": ss.blocked_free_chips,
            "used_shares": ss.used_shares,
            "total_shares": ss.total_shares,
            "utilization": ss.utilization,
            "fragmentation": ss.fragmentation(),
        }
    return out


def gang_fit_probe(extender: Extender, pod: PodInfo, total: int) -> bool:
    """Can this replica host the gang ICI-contiguously in ONE of its
    slices? The same search ``ensure_reservation`` runs — against the
    replica's epoch-cached snapshot, so the sweep this builds is the
    sweep the reservation reuses."""
    snap = extender.snapshots.current()
    shape = pod.group.shape if pod.group is not None else None
    for sid in snap.slice_ids():
        ss = snap.slice(sid)
        if ss.blocked_free_chips < total:
            continue
        coords = slicefit.find_slice_in(
            ss.blocked_sweep(),
            count=None if shape is not None else total,
            shape=shape,
            broken=ss.broken,
        )
        if coords is not None:
            return True
    return False


def gang_prepare_part(
    extender: Extender, pod: PodInfo, cpp: int, volumes: dict[str, int],
) -> dict[str, list[TopologyCoord]]:
    """One replica's PREPARE leg of the two-phase rendezvous: find one
    contiguous free box per requested slice (shrinking by chips_per_pod
    when fragmentation beat the router's gauge-planned volume) and
    reserve them through ``reserve_exact_split`` with a LOCAL-quorum
    group. Returns {slice id -> reserved coords}; raises GangError when
    the replica cannot cover any of the request (nothing reserved — the
    router aborts the rendezvous). A duplicate prepare is idempotent:
    an existing reservation for the key answers with its own parts."""
    assert pod.group is not None
    existing = extender.gang.reservation(pod.namespace, pod.group.name)
    if existing is not None:
        return {sid: sorted(coords)
                for sid, coords in existing.slice_coords.items()}
    snap = extender.snapshots.current()
    parts: dict[str, list[TopologyCoord]] = {}
    got = 0
    for sid in sorted(volumes):
        try:
            ss = snap.slice(sid)
        except KeyError:
            continue  # slice vanished since the gauge read: race
        vol = min(volumes[sid], (ss.blocked_free_chips // cpp) * cpp)
        while vol >= cpp:
            coords = slicefit.find_slice_in(
                ss.blocked_sweep(), count=vol, broken=ss.broken
            )
            if coords is not None:
                parts[sid] = list(coords)
                got += len(coords)
                break
            vol -= cpp
    if got == 0:
        raise GangError(
            f"gang {pod.namespace}/{pod.group.name}: no contiguous part "
            f"available (gauges raced an occupancy change); retry"
        )
    members = got // cpp
    local_pod = dc_replace(pod, group=PodGroup(
        name=pod.group.name, min_member=members,
        shape=None, allow_dcn=True,
    ))
    extender.gang.reserve_exact_split(local_pod, cpp, parts)
    return parts


def replica_summary(extender: Extender) -> dict[str, Any]:
    """One replica's rollup row: ledger/queue/gang counters plus the
    merged-observability feeds (latency windows, event counts, cycle
    stats) the router aggregates across the shard set."""
    st = extender.state
    share_counts: dict[str, list[int]] = {}
    used = total = 0
    for sid in st.slice_ids():
        u, t = st.slice_share_counts(sid)
        share_counts[sid] = [u, t]
        used += u
        total += t
    cycle = extender.cycle
    cycle_stats = None
    if cycle is not None:
        cycle_stats = dict(cycle.stats())
        cycle_stats["cycle_wall_total"] = cycle.cycle_wall_total
    out = {
        "slices": st.slice_ids(),
        "nodes": len(st.node_names()),
        "allocs": len(st.allocations()),
        "share_counts": share_counts,
        "used_shares": used,
        "total_shares": total,
        "utilization": used / total if total else 0.0,
        "binds_total": extender.binds_total,
        "preemptions": extender.preemptions,
        "queue_depth": cycle.queue_depth() if cycle is not None else 0,
        "snapshot_hits": extender.snapshots.hits,
        "snapshot_rebuilds": extender.snapshots.rebuilds,
        "audit": {
            "rate": extender.snapshots.audit_rate,
            "checks": extender.snapshots.audit_checks,
            "divergences": extender.snapshots.audit_divergences,
        },
        "events": extender.events.counts_by_reason(),
        "cycle": cycle_stats,
        "latencies": {h: list(w)
                      for h, w in extender.latencies.items()},
    }
    # federated lockgraph (ISSUE 18): with the dynamic lock-order
    # detector installed in THIS process (lock_monitor on), the
    # replica's observed edge set rides its summary row — the same
    # surface the subprocess transport already serves over
    # /worker/summary, so worker-process edges reach the router's
    # fleet-wide cycle merge with no new wire protocol. Key absent
    # when the monitor is off (off-is-off: summaries byte-identical).
    from tpukube.analysis import lockgraph

    mon = lockgraph.active()
    if mon is not None:
        out["lock_graph"] = mon.report()
    return out


# -- replica transports ------------------------------------------------------

class InProcessTransport:
    """The parity oracle: the replica is a live Extender object in this
    process, every call a direct method dispatch. This is the transport
    PR 13 shipped — deterministic, single-GIL — and stays the tier-1
    path; the subprocess transport below carries the identical surface
    over the extender webhook/HTTP contract."""

    mode = "inprocess"

    def __init__(self, extender: Extender):
        self.extender = extender

    # decision surface ------------------------------------------------------
    def handle(self, kind: str, body: Any) -> Any:
        return self.extender.handle(kind, body)

    def upsert_nodes(self, items: list[dict[str, Any]]) -> list[Any]:
        # ONE bulk-ingest decision per batch (ISSUE 15): the replica
        # ingests its whole shard through the cold-start fast path
        return self.extender.upsert_nodes_many(items)

    def admit_many(self, pods: list[PodInfo]) -> list[bool]:
        return [self.extender.admit(p) for p in pods]

    def plan_pending(self) -> int:
        return self.extender.plan_pending()

    def planned_nodes(self, keys: list[str]) -> dict[str, Optional[str]]:
        return {k: self.extender.planned_node(k) for k in keys}

    def bind_many(self, bodies: list[dict]) -> list[dict]:
        return [self.extender.handle("bind", b) for b in bodies]

    def release_many(self, pod_keys: list[str]) -> None:
        for key in pod_keys:
            self.extender.handle("release", {"pod_key": key})

    # gang / rendezvous surface ---------------------------------------------
    def gauges(self) -> dict[str, dict[str, Any]]:
        return replica_gauges(self.extender)

    def gang_fit(self, pod: PodInfo, total: int) -> bool:
        return gang_fit_probe(self.extender, pod, total)

    def gang_prepare(self, pod: PodInfo, cpp: int,
                     volumes: dict[str, int]) -> dict[str, list]:
        return gang_prepare_part(self.extender, pod, cpp, volumes)

    def gang_drop(self, key: tuple[str, str]) -> None:
        self.extender.gang.drop_reservation(key)

    def gang_dissolve(self, key: tuple[str, str]) -> None:
        self.extender.gang.dissolve(key)

    def gang_reservation(self, key: tuple[str, str]) -> Optional[dict]:
        res = self.extender.gang.reservation(*key)
        if res is None:
            return None
        return {
            "committed": res.committed,
            "slices": {sid: sorted(coords)
                       for sid, coords in res.slice_coords.items()},
        }

    def gang_sweep(self) -> None:
        self.extender.gang.sweep()

    # read views ------------------------------------------------------------
    def allocations(self) -> list[AllocResult]:
        return self.extender.state.allocations()

    def allocs_since(self, cursor) -> Optional[dict]:
        return self.extender.state.allocs_since(cursor)

    def allocation(self, pod_key: str) -> Optional[AllocResult]:
        return self.extender.state.allocation(pod_key)

    def node(self, name: str):
        return self.extender.state.node(name)

    def node_names(self) -> tuple[str, ...]:
        return self.extender.state.node_names()

    def slice_ids(self) -> list[str]:
        return self.extender.state.slice_ids()

    def gang_snapshot(self) -> list[dict[str, Any]]:
        return self.extender.gang_snapshot()

    def alloc_snapshot(self) -> list[dict[str, Any]]:
        return self.extender.alloc_snapshot()

    def summary(self) -> dict[str, Any]:
        return replica_summary(self.extender)

    def latencies(self) -> dict[str, list[float]]:
        return {h: list(w) for h, w in self.extender.latencies.items()}

    def counts_by_reason(self) -> dict[str, int]:
        return self.extender.events.counts_by_reason()

    def events_emit(self, *args, **kwargs) -> None:
        self.extender.events.emit(*args, **kwargs)

    # federated observability -----------------------------------------------
    def explain(self, pod_key: str) -> Optional[dict[str, Any]]:
        dlog = self.extender.decisions
        return dlog.explain(pod_key) if dlog is not None else None

    def events_query(self, reason=None, pod=None, node=None,
                     since=None) -> list[dict[str, Any]]:
        return self.extender.events.events(reason=reason, pod=pod,
                                           node=node, since=since)

    def metrics_text(self) -> str:
        from tpukube.metrics import render_extender_metrics

        return render_extender_metrics(self.extender)

    def statusz_doc(self) -> dict[str, Any]:
        from tpukube.obs.statusz import extender_statusz

        return extender_statusz(self.extender)

    def trace_events(self, since_seq: int = 0) -> list[dict[str, Any]]:
        tr = self.extender.trace
        return tr.events(since_seq=since_seq) if tr is not None else []

    def capacity_doc(self, since=None) -> Optional[dict[str, Any]]:
        cap = self.extender.capacity
        return cap.capacity_doc(since=since) if cap is not None else None

    def capacity_probe(self, count=None, shape=None,
                       chips_per_pod=1) -> Optional[dict[str, Any]]:
        cap = self.extender.capacity
        if cap is None:
            return None
        return cap.probe(count=count, shape=shape,
                         chips_per_pod=chips_per_pod)

    def wire_snapshot(self) -> Optional[dict[str, Any]]:
        return None  # direct dispatch: nothing crosses a wire

    # lifecycle -------------------------------------------------------------
    def rebuild_from_pods(self, pods: list[dict[str, str]]) -> int:
        return self.extender.rebuild_from_pods(pods)

    def recover(self, node_objs: list[dict],
                pod_objs: list[dict]) -> dict:
        """Warm restart from the replica's own journal segment
        (checkpoint + WAL replay + reconcile against the provided
        node/pod truth). ``{"recover_error": ...}`` when the journal
        cannot produce a trustworthy base — the router then falls back
        to the cold full re-ingest on a FRESH replica."""
        from tpukube.sched import journal as journal_mod

        ext = self.extender
        if ext.journal is None:
            return {"recover_error": "journal disabled"}
        try:
            stats = journal_mod.recover_extender(
                ext, _ListApi(node_objs, pod_objs))
        except journal_mod.JournalError as e:
            ext.journal.crash()
            ext.state.retire()
            return {"recover_error": str(e)}
        return {"stats": stats,
                "restored": len(ext.state.allocations())}

    def drain_evictions(self) -> list[str]:
        # the in-process replicas share the router's eviction deque
        # (eviction_sink) — there is nothing replica-local to pull
        return []

    def advance(self, seconds: float) -> None:
        pass  # shares the router process's clock

    def healthz(self) -> bool:
        return True

    def set_evict_precheck(self, fn) -> None:
        self.extender.evict_precheck = fn

    def set_binder(self, fn) -> None:
        self.extender.binder = fn

    def set_degraded_gate(self, fn) -> None:
        self.extender.degraded_gate = fn

    def kill(self) -> None:
        # process death is modeled by the router (journal crash +
        # ledger retire); nothing transport-level to tear down
        pass

    def close(self) -> None:
        ext = self.extender
        if ext.trace is not None:
            ext.trace.close()
        if ext.capacity is not None:
            ext.capacity.close()
        ext.events.close()
        if ext.journal is not None:
            ext.journal.close()
            ext.state.retire()


class SubprocessTransport:
    """One planner daemon per replica: spawns a ``tpukube-shard-worker``
    subprocess (an Extender behind the standard webhook app plus the
    /worker/* routes of sched/shardworker.py) and speaks the same
    transport surface over HTTP. Requests on ONE replica are ordered
    (a single kept-alive connection behind a lock — binds and
    rendezvous prepares arrive in call order); the ROUTER fans calls
    out to distinct replicas concurrently, which is where the
    multi-core speedup lives. A connection failure marks the replica
    dead through ``on_down`` — exactly ``crash_replica`` semantics."""

    mode = "subprocess"
    #: no live Extender object in this process (tests and the router's
    #: in-process-only seams check for None)
    extender = None

    SPAWN_TIMEOUT_S = 30.0
    RTT_WINDOW = 1024

    def __init__(self, index: int, config: TpuKubeConfig,
                 fake_clock: bool, on_down=None):
        self.index = index
        self.on_down = on_down
        self.down = False
        self.health_checks = 0
        self.health_failures = 0
        self.rtt_window: deque[float] = deque(maxlen=self.RTT_WINDOW)
        self.rtt_sum = 0.0
        self.rtt_count = 0
        # wire-cost accounting (the codec item's baseline): request and
        # response bytes as they cross this transport, total and per op
        # (op = the /worker/* route tail). Updated under _lock with the
        # RTT stats; read via wire_snapshot().
        self.wire_tx = 0
        self.wire_rx = 0
        self.wire_by_op: dict[str, dict[str, Any]] = {}
        # wire codec (ISSUE 20, sched/wirecodec.py): json (default,
        # the parity oracle) or binary (TKW1 frames). raw counters
        # track pre-compression frame bytes so /statusz can cite a
        # per-op compression ratio without re-serializing to JSON.
        self.wire_codec = config.wire_codec
        self.wire_compress_min_bytes = config.wire_compress_min_bytes
        self.wire_raw_tx = 0
        self.wire_raw_rx = 0
        # Per-connection negotiated peer capability: None = unknown
        # (requests go out as JSON with an Accept probe), True = the
        # peer answered in TKW1, so request BODIES switch to binary
        # too. Reset to None whenever the kept-alive connection is
        # torn down — a respawned worker re-handshakes from JSON, so a
        # binary router over a restarted (possibly older, JSON-only)
        # worker degrades cleanly per replica.
        self._peer_binary: Optional[bool] = None
        #: optional (index, op, tx_bytes, rx_bytes, rtt_s) hook the
        #: router uses to feed its fan-out flight recorder; called
        #: outside the transport lock, after each completed request
        self.on_wire = None
        self._lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._port = _free_port()
        self._cfg_path = self._write_config(config)
        cmd = [sys.executable, "-m", "tpukube.cli", "shard-worker",
               "--config", self._cfg_path,
               "--port", str(self._port)]
        if fake_clock:
            cmd.append("--fake-clock")
        # scrub TPUKUBE_* so the resolved per-replica YAML is the ONE
        # config source — an inherited TPUKUBE_PLANNER_REPLICAS=4 must
        # not make each worker try to be a router itself
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("TPUKUBE_")}
        self._proc = subprocess.Popen(
            cmd, env=env, stdin=subprocess.DEVNULL,
            stdout=subprocess.DEVNULL,
        )
        self._wait_ready()

    def _write_config(self, config: TpuKubeConfig) -> str:
        import dataclasses

        import yaml

        doc = {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in dataclasses.asdict(config).items()
        }
        # the worker IS one planner: never a router, never recursive
        doc["planner_replicas"] = 1
        doc["shard_transport"] = "inprocess"
        fd, path = tempfile.mkstemp(prefix=f"tpukube-r{self.index}-",
                                    suffix=".yaml")
        with os.fdopen(fd, "w") as f:
            yaml.safe_dump(doc, f)
        return path

    def _wait_ready(self) -> None:
        deadline = time.monotonic() + self.SPAWN_TIMEOUT_S
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                raise ShardError(
                    f"shard worker r{self.index} exited with "
                    f"{self._proc.returncode} before serving"
                )
            try:
                if self.healthz(timeout=1.0):
                    # the spawn-wait probes are EXPECTED to fail until
                    # the daemon serves: they are not health signal
                    self.health_checks = 0
                    self.health_failures = 0
                    if self.wire_codec == "binary":
                        # complete the codec handshake NOW with one
                        # cheap op, or the first heavy call — usually
                        # the fleet-sized cold-start ingest, the very
                        # body the codec exists for — would ride the
                        # JSON probe
                        try:
                            self._request("GET", "/worker/gauges",
                                          timeout=5.0,
                                          mark_down=False)
                        except (ReplicaUnavailable, ShardError):
                            pass  # probe only; requests renegotiate
                    return
            except ReplicaUnavailable:
                pass
            time.sleep(0.05)
        self.kill()
        raise ShardError(
            f"shard worker r{self.index} did not serve /healthz "
            f"within {self.SPAWN_TIMEOUT_S}s"
        )

    # -- wire ---------------------------------------------------------------
    def _request(self, method: str, path: str,
                 body: Optional[dict] = None, timeout: float = 60.0,
                 mark_down: bool = True, as_text: bool = False) -> Any:
        # Only the /worker/* op surface negotiates the binary codec;
        # exposition passthrough (/metrics, /statusz, /healthz, ...)
        # stays JSON/text regardless.
        wire_op = path.startswith("/worker/")
        negotiate = (wire_op and not as_text
                     and self.wire_codec == "binary")
        # _peer_binary is read without the lock: requests on one
        # replica serialize behind _lock anyway, and the worst a stale
        # read costs is one more JSON-bodied probe request.
        req_codec = "json"
        raw_tx = 0
        if body is not None:
            if negotiate and self._peer_binary:
                payload, raw_tx = wirecodec.encode_frame(
                    body, self.wire_compress_min_bytes)
                headers = {
                    "Content-Type": wirecodec.WIRE_CONTENT_TYPE}
                req_codec = "binary"
            else:
                payload = wirecodec.dumps_json(body)
                headers = {
                    "Content-Type": wirecodec.JSON_CONTENT_TYPE}
                raw_tx = len(payload)
        else:
            payload = None
            headers = {}
        if negotiate:
            # capability probe: a TKW1-speaking worker answers in
            # kind; a JSON-only worker ignores it — the per-replica
            # rolling-upgrade degrade
            headers["Accept"] = wirecodec.WIRE_CONTENT_TYPE
        ctx = trace_mod.TRACE_CONTEXT.get()
        if ctx is not None:
            # propagate the router's trace context so the worker tags
            # its decision records and timeline spans with it
            headers["X-Tpukube-Trace"] = \
                f"{ctx.get('trace', '')}/{ctx.get('parent', '')}"
        op = path.split("?", 1)[0].lstrip("/")
        if op.startswith("worker/"):
            op = op[len("worker/"):]
        op = op.replace("/", "_")
        t0 = time.perf_counter()
        with self._lock:
            if self.down:
                raise ReplicaUnavailable(
                    f"replica r{self.index} is down"
                )
            try:
                conn = self._conn
                if conn is None:
                    conn = self._conn = http.client.HTTPConnection(
                        "127.0.0.1", self._port, timeout=timeout
                    )
                elif conn.sock is not None:
                    # the kept-alive socket's timeout is pinned at
                    # connect time: re-arm it PER REQUEST, or a quick
                    # health probe's 2s budget would cap every later
                    # heavy call (a 10k-node upsert, a 2k-pod plan)
                    # and read as replica death
                    conn.sock.settimeout(timeout)
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except (http.client.HTTPException, OSError) as e:
                if self._conn is not None:
                    self._conn.close()
                    self._conn = None
                # fresh connection means a possibly fresh peer (a
                # respawned worker): renegotiate the codec from JSON
                self._peer_binary = None
                # bill the failed request too — an unaccounted retry
                # storm is exactly the traffic this counter exists to
                # expose (rx stays 0: nothing usable came back)
                tx = len(payload or b"")
                self.wire_tx += tx
                self.wire_raw_tx += raw_tx
                cell = self.wire_by_op.get(op)
                if cell is None:
                    cell = self.wire_by_op[op] = \
                        {"tx": 0, "rx": 0, "calls": 0}
                cell["tx"] += tx
                cell["calls"] += 1
                cell["failures"] = cell.get("failures", 0) + 1
                if req_codec == "binary":
                    cell["codec"] = "binary"
                    cell["raw_tx"] = cell.get("raw_tx", 0) + raw_tx
                    cell["raw_rx"] = cell.get("raw_rx", 0)
                if mark_down:
                    self._mark_down_locked(e)
                raise ReplicaUnavailable(
                    f"replica r{self.index} unreachable: {e}"
                ) from e
            dt = time.perf_counter() - t0
            self.rtt_window.append(dt)
            self.rtt_sum += dt
            self.rtt_count += 1
            resp_ct = (resp.getheader("Content-Type") or "").split(
                ";", 1)[0].strip()
            resp_binary = resp_ct == wirecodec.WIRE_CONTENT_TYPE
            if negotiate and resp_binary and resp.status < 400:
                # the worker answered TKW1: switch request bodies to
                # binary for the rest of this connection
                self._peer_binary = True
            tx, rx = len(payload or b""), len(raw)
            self.wire_tx += tx
            self.wire_rx += rx
            self.wire_raw_tx += raw_tx
            if not resp_binary:
                self.wire_raw_rx += rx
            cell = self.wire_by_op.get(op)
            if cell is None:
                cell = self.wire_by_op[op] = \
                    {"tx": 0, "rx": 0, "calls": 0}
            cell["tx"] += tx
            cell["rx"] += rx
            cell["calls"] += 1
            if req_codec == "binary" or resp_binary:
                # tag the cell with the codec that actually crossed
                # the wire (absence of the tag = pure JSON, so the
                # default-codec cell shape is unchanged) and track
                # pre-compression frame bytes for the ratio exposition
                cell["codec"] = "binary"
                cell["raw_tx"] = cell.get("raw_tx", 0) + raw_tx
                cell.setdefault("raw_rx", 0)
        if self.on_wire is not None:
            self.on_wire(self.index, op, tx, rx, dt,
                         "binary" if (req_codec == "binary"
                                      or resp_binary) else "json")
        if resp.status >= 400:
            raise ShardError(
                f"replica r{self.index} {path}: HTTP {resp.status}: "
                f"{raw.decode(errors='replace')[:200]}"
            )
        if as_text:
            return raw.decode("utf-8", errors="replace")
        if not raw:
            return None
        if resp_binary:
            # decode outside the transport lock (a fleet-sized audit
            # read must not stall the next request behind its decode)
            try:
                out, raw_rx = wirecodec.decode_frame_ex(raw)
            except wirecodec.WireCodecError as e:
                raise ShardError(
                    f"replica r{self.index} {path}: undecodable "
                    f"wire frame: {e}"
                ) from e
            with self._lock:
                self.wire_raw_rx += raw_rx
                cell = self.wire_by_op.get(op)
                if cell is not None:
                    cell["raw_rx"] = cell.get("raw_rx", 0) + raw_rx
            return out
        return json.loads(raw)

    def _mark_down_locked(self, err: Exception) -> None:
        if not self.down:
            self.down = True
            log.error("replica r%d transport failed (%s); marking the "
                      "replica dead", self.index, err)
            if self.on_down is not None:
                self.on_down(self.index)

    # decision surface ------------------------------------------------------
    def handle(self, kind: str, body: Any) -> Any:
        out = self._request("POST", "/worker/handle",
                            {"kind": kind, "body": body})
        if isinstance(out, dict) and "schema_error" in out:
            # re-raise the exception type the in-process dispatch would
            # have propagated — the HTTP layer above maps it to 400
            raise kube.KubeSchemaError(out["schema_error"])
        return out

    def upsert_nodes(self, items: list[dict[str, Any]]) -> list[Any]:
        return self._request("POST", "/worker/upsert",
                             {"items": items})["results"]

    def admit_many(self, pods: list[PodInfo]) -> list[bool]:
        return self._request("POST", "/worker/admit", {
            "pods": [kube.pod_to_k8s(p) for p in pods],
        })["admitted"]

    def plan_pending(self) -> int:
        return self._request("POST", "/worker/plan", {})["planned"]

    def planned_nodes(self, keys: list[str]) -> dict[str, Optional[str]]:
        return self._request("POST", "/worker/planned",
                             {"keys": list(keys)})["nodes"]

    def bind_many(self, bodies: list[dict]) -> list[dict]:
        return self._request("POST", "/worker/bind",
                             {"bodies": bodies})["results"]

    def release_many(self, pod_keys: list[str]) -> None:
        self._request("POST", "/worker/release",
                      {"keys": list(pod_keys)})

    # gang / rendezvous surface ---------------------------------------------
    def gauges(self) -> dict[str, dict[str, Any]]:
        return self._request("GET", "/worker/gauges")["slices"]

    def _gang(self, op: str, **kw) -> Any:
        out = self._request("POST", "/worker/gang", {"op": op, **kw})
        err = out.get("error")
        if err:
            # the worker maps expected races (box re-occupied, slice
            # gone) to typed errors so the router degrades exactly as
            # the in-process prepare would
            if out.get("kind") == "state":
                raise StateError(err)
            raise GangError(err)
        return out

    def gang_fit(self, pod: PodInfo, total: int) -> bool:
        return self._gang("fit", pod=kube.pod_to_k8s(pod),
                          total=total)["fits"]

    def gang_prepare(self, pod: PodInfo, cpp: int,
                     volumes: dict[str, int]) -> dict[str, list]:
        out = self._gang("prepare", pod=kube.pod_to_k8s(pod), cpp=cpp,
                         volumes=volumes)
        return {
            sid: [TopologyCoord.of(c) for c in coords]
            for sid, coords in out["parts"].items()
        }

    def gang_drop(self, key: tuple[str, str]) -> None:
        self._gang("drop", namespace=key[0], name=key[1])

    def gang_dissolve(self, key: tuple[str, str]) -> None:
        self._gang("dissolve", namespace=key[0], name=key[1])

    def gang_reservation(self, key: tuple[str, str]) -> Optional[dict]:
        out = self._gang("reservation", namespace=key[0],
                         name=key[1])["reservation"]
        if out is None:
            return None
        out["slices"] = {
            sid: [TopologyCoord.of(c) for c in coords]
            for sid, coords in (out.get("slices") or {}).items()
        }
        return out

    def gang_sweep(self) -> None:
        self._gang("sweep")

    # read views ------------------------------------------------------------
    def allocations(self) -> list[AllocResult]:
        return self._decode_allocs(
            self._request("GET", "/worker/allocs")["allocs"])

    def _decode_allocs(self, objs: list) -> list[AllocResult]:
        allocs = []
        for obj in objs:
            try:
                allocs.append(codec.alloc_from_obj(obj))
            except codec.CodecError as e:
                log.error("replica r%d sent an undecodable alloc: %s",
                          self.index, e)
        return allocs

    def allocs_since(self, cursor) -> Optional[dict]:
        out = self._request("POST", "/worker/allocs_since",
                            {"cursor": cursor})
        if out is None or out.get("disabled"):
            return None
        res: dict[str, Any] = {
            "cursor": out["cursor"],
            "bytes": int(out.get("bytes", 0)),
        }
        if "full" in out:
            res["full"] = self._decode_allocs(out["full"])
        else:
            res["adds"] = self._decode_allocs(out["adds"])
            res["removes"] = [str(k) for k in out["removes"]]
        return res

    def allocation(self, pod_key: str) -> Optional[AllocResult]:
        from urllib.parse import quote

        out = self._request(
            "GET", f"/worker/alloc?pod={quote(pod_key, safe='')}"
        )["alloc"]
        if out is None:
            return None
        try:
            return codec.alloc_from_obj(out)
        except codec.CodecError as e:
            log.error("replica r%d sent an undecodable alloc for %s: "
                      "%s", self.index, pod_key, e)
            return None

    def node(self, name: str):
        # NodeView objects do not cross the process boundary; router
        # callers needing node payloads read them from the pod/node
        # store, not from a remote replica's in-memory view
        return None

    def node_names(self) -> tuple[str, ...]:
        return tuple(self._request("GET", "/worker/nodes")["names"])

    def slice_ids(self) -> list[str]:
        return list(self._request("GET", "/worker/summary")["slices"])

    def gang_snapshot(self) -> list[dict[str, Any]]:
        return self._request("GET", "/state/gangs")

    def alloc_snapshot(self) -> list[dict[str, Any]]:
        return self._request("GET", "/state/allocs")

    def summary(self) -> dict[str, Any]:
        return self._request("GET", "/worker/summary")

    def latencies(self) -> dict[str, list[float]]:
        return self._request("GET", "/worker/summary")["latencies"]

    def counts_by_reason(self) -> dict[str, int]:
        return self._request("GET", "/worker/summary")["events"]

    def events_emit(self, reason: str, obj: str = "", message: str = "",
                    **kwargs) -> None:
        self._request("POST", "/worker/emit", {
            "reason": reason, "obj": obj, "message": message, **kwargs,
        })

    # federated observability -----------------------------------------------
    def explain(self, pod_key: str) -> Optional[dict[str, Any]]:
        from urllib.parse import quote

        try:
            return self._request(
                "GET", f"/explain?pod={quote(pod_key, safe='')}")
        except ShardError:
            return None  # provenance disabled on the worker (404)

    def events_query(self, reason=None, pod=None, node=None,
                     since=None) -> list[dict[str, Any]]:
        from urllib.parse import urlencode

        q = {k: v for k, v in (("reason", reason), ("pod", pod),
                               ("node", node), ("since", since))
             if v is not None}
        path = "/events" + (f"?{urlencode(q)}" if q else "")
        return self._request("GET", path) or []

    def metrics_text(self) -> str:
        return self._request("GET", "/metrics", as_text=True)

    def statusz_doc(self) -> dict[str, Any]:
        return self._request("GET", "/statusz")

    def trace_events(self, since_seq: int = 0) -> list[dict[str, Any]]:
        try:
            return self._request(
                "GET", f"/trace?since={since_seq}") or []
        except ShardError:
            return []  # tracing disabled on the worker (404)

    def capacity_doc(self, since=None) -> Optional[dict[str, Any]]:
        path = "/capacity" + (f"?since={since}" if since is not None
                              else "")
        try:
            return self._request("GET", path)
        except ShardError:
            return None  # capacity disabled on the worker (404)

    def capacity_probe(self, count=None, shape=None,
                       chips_per_pod=1) -> Optional[dict[str, Any]]:
        from urllib.parse import urlencode

        q: dict[str, Any] = {"chips_per_pod": chips_per_pod}
        if count is not None:
            q["count"] = count
        if shape is not None:
            q["shape"] = "x".join(str(d) for d in shape)
        try:
            return self._request(
                "GET", f"/capacity/probe?{urlencode(q)}")
        except ShardError:
            return None  # capacity disabled on the worker (404)

    def wire_snapshot(self) -> dict[str, Any]:
        """Cumulative request/response byte counters, total and per op
        — the baseline the ROADMAP codec item will be judged against."""
        with self._lock:
            snap = {
                "tx": self.wire_tx,
                "rx": self.wire_rx,
                "by_op": {op: dict(c)
                          for op, c in self.wire_by_op.items()},
            }
            if self.wire_codec != "json":
                # pre-compression frame bytes next to the wire bytes:
                # saved = raw - wire, without re-serializing to JSON.
                # Keys appear only with the codec on so the default
                # plane's snapshot/statusz stays byte-identical.
                snap["codec"] = self.wire_codec
                snap["raw_tx"] = self.wire_raw_tx
                snap["raw_rx"] = self.wire_raw_rx
            return snap

    # lifecycle -------------------------------------------------------------
    def rebuild_from_pods(self, pods: list[dict[str, str]]) -> int:
        return self._request("POST", "/worker/rebuild",
                             {"pods": pods})["restored"]

    def recover(self, node_objs: list[dict],
                pod_objs: list[dict]) -> dict:
        # recovery replays the worker's whole journal segment and
        # reconciles a fleet-sized feed: give it the heavy-call budget
        return self._request("POST", "/worker/recover",
                             {"nodes": node_objs, "pods": pod_objs},
                             timeout=300.0)

    def drain_evictions(self) -> list[str]:
        return self._request("POST", "/worker/evictions", {})["pods"]

    def advance(self, seconds: float) -> None:
        self._request("POST", "/worker/advance", {"seconds": seconds})

    def healthz(self, timeout: float = 2.0) -> bool:
        self.health_checks += 1
        try:
            out = self._request("GET", "/healthz", timeout=timeout,
                                mark_down=False)
        except (ReplicaUnavailable, ShardError):
            self.health_failures += 1
            raise ReplicaUnavailable(
                f"replica r{self.index}: health check failed"
            ) from None
        return bool(out.get("ok"))

    def set_evict_precheck(self, fn) -> None:
        # the worker daemon owns its own apiserver wiring; the sim
        # worker runs precheck-less (no PDBs), matching the harness's
        # trivially-true precheck
        pass

    def set_binder(self, fn) -> None:
        pass  # the router process applies bind annotations (sim store)

    def set_degraded_gate(self, fn) -> None:
        pass  # a real worker daemon wires its own circuit -> gate

    def rtt_snapshot(self) -> list[float]:
        with self._lock:
            return list(self.rtt_window)

    def kill(self) -> None:
        """SIGKILL — process death, nothing flushed (the chaos story's
        crash_replica over a real process)."""
        with self._lock:
            self.down = True
            if self._conn is not None:
                self._conn.close()
                self._conn = None
        if self._proc.poll() is None:
            self._proc.kill()
        self._proc.wait(timeout=10)
        self._cleanup_config()

    def close(self) -> None:
        """Graceful stop (harness shutdown)."""
        with self._lock:
            self.down = True
            if self._conn is not None:
                self._conn.close()
                self._conn = None
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=10)
        self._cleanup_config()

    def _cleanup_config(self) -> None:
        try:
            os.unlink(self._cfg_path)
        except OSError:
            pass  # already removed (double close) — nothing to clean


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class PlannerReplica:
    """One shard of the control plane: index + its transport + liveness.
    ``alive=False`` models a partitioned OR killed replica — the
    router stops routing to it and the rendezvous janitor treats its
    uncommitted parts as lost. ``killed=True`` additionally marks the
    in-memory state as GONE (process death): the federated read views
    must not serve the corpse's ledger — a dead shard's pods are
    ledger-absent until the warm restart, and the chaos invariants
    must see exactly that. ``transport`` is the replica's decision
    surface: an :class:`InProcessTransport` (a live Extender in this
    process — the parity oracle) or a :class:`SubprocessTransport`
    (one planner daemon per replica over the webhook HTTP contract)."""

    __slots__ = ("index", "transport", "alive", "killed", "pods_routed")

    def __init__(self, index: int, transport):
        self.index = index
        self.transport = transport
        self.alive = True
        self.killed = False
        self.pods_routed = 0

    @property
    def extender(self) -> Optional[Extender]:
        """The replica's in-process Extender (None for a subprocess
        replica — its extender lives in the worker daemon)."""
        return self.transport.extender

    @property
    def name(self) -> str:
        return f"r{self.index}"


class _Rendezvous:
    """Router-side record of one DCN gang's prepared parts."""

    __slots__ = ("key", "parts", "local_min", "created", "committed",
                 "member_target")

    def __init__(self, key: tuple[str, str],
                 parts: dict[int, dict[str, list[TopologyCoord]]],
                 local_min: dict[int, int], created: float):
        self.key = key
        #: replica index -> {slice id -> reserved coords}
        self.parts = parts
        #: replica index -> that part's member quorum
        self.local_min = local_min
        self.created = created
        self.committed = False
        #: pod key -> its part's replica index: STICKY member routing,
        #: capped per part at local_min — the driver path admits every
        #: member before any binds, so ``assignable`` cannot spread
        #: them; the router must (and a member's filter, prioritize,
        #: and bind must all land on the same part)
        self.member_target: dict[str, int] = {}


class _FederatedState:
    """Read-only ledger view over every replica (the surface the
    apiserver loops and chaos checkers consume: ``allocations``,
    ``allocation``, ``utilization``, ``node_names``). Mutations never
    come through here — they route via ``ShardRouter.handle``. A
    KILLED replica's state is excluded: its in-memory ledger died
    with the process, and serving the corpse would let the chaos
    invariants false-negative on exactly the divergence a dead shard
    creates (a partitioned replica's state, by contrast, is real and
    still served)."""

    def __init__(self, router: "ShardRouter"):
        self._router = router

    def _live(self) -> list[PlannerReplica]:
        return [r for r in self._router.replicas if not r.killed]

    def allocations(self) -> list:
        # fanned out: in process mode each replica serializes its own
        # ledger concurrently (the lifecycle resync reads this every
        # churn wave — serial fetches would re-serialize the whole
        # fleet through one connection at a time)
        results = self._router._fan_out(
            self._live(), lambda rep: rep.transport.allocations()
        )
        out: list = []
        for allocs in results.values():
            out.extend(allocs)
        return out

    def allocs_since(self, cursor) -> Optional[dict]:
        """Federated incremental resync (ISSUE 15): fan ``allocs_since``
        out per live replica (concurrently in process mode) and merge.
        The merged answer is INCREMENTAL only when the answering
        replica set matches the cursor's and every replica answered
        incrementally; anything else — a replica killed, healed,
        restarted (fresh incarnation), gapped, or simply missing from
        the last cursor — degrades to a merged FULL answer, never a
        stale one. A churn wave against a stable shard set therefore
        moves O(changed-allocs) wire bytes instead of every replica's
        whole ledger. None when any replica runs without the log
        (consumers then keep the legacy full read)."""
        router = self._router
        reps = self._live()
        prev = cursor if isinstance(cursor, dict) else None
        results = router._fan_out(
            reps,
            lambda rep: rep.transport.allocs_since(
                (prev or {}).get(rep.name)),
        )
        if not results or any(r is None for r in results.values()):
            return None  # a replica has no change log: legacy reads
        names = {router.replicas[i].name for i in results}
        new_cursor = {router.replicas[i].name: r["cursor"]
                      for i, r in results.items()}
        total_bytes = sum(int(r.get("bytes", 0))
                          for r in results.values())
        if (prev is not None and set(prev) == names
                and all("full" not in r for r in results.values())):
            adds: list = []
            removes: list[str] = []
            for r in results.values():
                adds.extend(r["adds"])
                removes.extend(r["removes"])
            return {"cursor": new_cursor, "adds": adds,
                    "removes": removes, "bytes": total_bytes}
        # full merge: replicas that answered incrementally re-read
        # their full set (rare — replica-set churn or a gap); changes
        # racing between a replica's cursor and its full read are
        # simply re-delivered by the next delta, which the consumer's
        # mirror absorbs idempotently
        full: list = []
        need = [router.replicas[i] for i, r in results.items()
                if "full" not in r]
        refetch = router._fan_out(
            need, lambda rep: rep.transport.allocations()
        )
        from tpukube.sched.state import _alloc_bytes

        for i, r in results.items():
            if "full" in r:
                full.extend(r["full"])
            elif i in refetch:
                # the refetched ledger is wire traffic too (on TOP of
                # the superseded incremental answer): count it, or the
                # bytes counter understates exactly the expensive
                # rounds it exists to expose
                full.extend(refetch[i])
                total_bytes += _alloc_bytes(refetch[i])
            else:
                # died between the two reads: its allocs drop from the
                # cursor too, so the next round full-reads again
                new_cursor.pop(router.replicas[i].name, None)
        return {"cursor": new_cursor, "full": full,
                "bytes": total_bytes}

    def allocation(self, pod_key: str):
        if self._router.mode == "subprocess":
            # bind answers prime this cache; a hit saves the lifecycle
            # loop one HTTP read per released pod (stale-yes is safe:
            # the routed release on an already-released pod is a no-op)
            cached = self._router._alloc_cache.get(pod_key)
            if cached is not None:
                return cached
        # the router's pod->replica affinity answers most lookups with
        # one targeted read; an unmapped key scans the live set
        idx = self._router._pod_replica.get(pod_key)
        reps = ([self._router.replicas[idx]] if idx is not None
                else self._live())
        for rep in reps:
            if rep.killed:
                continue
            try:
                a = rep.transport.allocation(pod_key)
            except ReplicaUnavailable:
                continue
            if a is not None:
                return a
        return None

    def priority_of(self, pod_key: str) -> int:
        a = self.allocation(pod_key)
        return a.priority if a is not None else 0

    def node(self, name: str):
        idx = self._router._node_replica.get(name)
        reps = (
            [self._router.replicas[idx]] if idx is not None
            else self._router.replicas
        )
        for rep in reps:
            if rep.killed:
                continue
            view = rep.transport.node(name)
            if view is not None:
                return view
        return None

    def node_names(self) -> tuple[str, ...]:
        out: list[str] = []
        for rep in self._live():
            try:
                out.extend(rep.transport.node_names())
            except ReplicaUnavailable:
                continue
        return tuple(sorted(out))

    def slice_ids(self) -> list[str]:
        out: list[str] = []
        for rep in self._live():
            try:
                out.extend(rep.transport.slice_ids())
            except ReplicaUnavailable:
                continue
        return sorted(out)

    def utilization(self) -> float:
        used = total = 0
        for rep in self._live():
            try:
                s = rep.transport.summary()
            except ReplicaUnavailable:
                continue
            used += s["used_shares"]
            total += s["total_shares"]
        return used / total if total else 0.0

    def retire(self) -> None:
        for rep in self._router.replicas:
            ext = rep.extender
            if ext is not None:
                ext.state.retire()


class _RouterCycle:
    """Aggregated batch-planner stats in the shape scenario drivers
    read (``extender.cycle.stats()``)."""

    def __init__(self, router: "ShardRouter"):
        self._router = router

    def _stats_rows(self) -> list[tuple[str, dict[str, Any]]]:
        out = []
        for rep in self._router.replicas:
            if rep.killed:
                continue
            try:
                s = rep.transport.summary().get("cycle")
            except ReplicaUnavailable:
                continue
            if s is not None:
                out.append((rep.name, s))
        return out

    @property
    def cycles(self) -> int:
        return sum(p["cycles"] for _, p in self._stats_rows())

    def stats(self) -> dict[str, Any]:
        rows = self._stats_rows()
        per = [p for _, p in rows]
        if not per:
            return {"enabled": False}
        summed = {
            k: sum(p[k] for p in per)
            for k in (
                "cycles", "pods_planned", "queue_depth", "plans_live",
                "assumes", "assume_undos", "fast_patches",
                "fast_rebuilds", "gang_batches", "gang_batch_members",
                "plan_hits", "plan_misses",
            )
        }
        lookups = summed["plan_hits"] + summed["plan_misses"]
        wall_total = sum(p["cycle_wall_total"] for p in per)
        summed.update({
            "enabled": True,
            "replicas": len(per),
            "plan_hit_ratio": (round(summed["plan_hits"] / lookups, 4)
                               if lookups else None),
            "plan_ms_per_pod": (
                round(1000 * wall_total / summed["pods_planned"], 4)
                if summed["pods_planned"] else None
            ),
            "per_replica": {
                name: {
                    "pods_planned": p["pods_planned"],
                    "cycles": p["cycles"],
                    "plan_ms_per_pod": p["plan_ms_per_pod"],
                }
                for name, p in rows
            },
        })
        return summed


class _MergedEvents:
    """Event-journal rollup over the replicas (scenario result code
    reads ``counts_by_reason``; the harness calls ``close``)."""

    def __init__(self, router: "ShardRouter"):
        self._router = router

    def counts_by_reason(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for rep in self._router.replicas:
            if rep.killed:
                continue
            try:
                counts = rep.transport.counts_by_reason()
            except ReplicaUnavailable:
                continue
            for reason, n in counts.items():
                out[reason] = out.get(reason, 0) + n
        return out

    def emit(self, *args, **kwargs) -> None:
        # router-level events land on replica 0's journal (the
        # rendezvous coordinator's channel)
        try:
            self._router.replicas[0].transport.events_emit(*args,
                                                           **kwargs)
        except ReplicaUnavailable:
            log.warning("router event %s lost: replica r0 unreachable",
                        args[0] if args else kwargs.get("reason"))

    def close(self) -> None:
        for rep in self._router.replicas:
            ext = rep.extender
            if ext is not None:
                ext.events.close()


class ShardRouter:
    """N planner replicas behind one decision surface (see module
    docstring). With ``planner_replicas == 1`` every entry point
    delegates verbatim to the sole Extender — the parity gate."""

    def __init__(self, config: TpuKubeConfig, clock=None):
        n = config.planner_replicas
        if n < 1:
            raise ShardError("planner_replicas must be >= 1")
        self.config = config
        self.mode = config.shard_transport
        from tpukube.core.clock import SYSTEM

        self.clock = clock if clock is not None else SYSTEM
        #: ONE eviction bus across replicas: in-process replicas share
        #: it directly (eviction_sink); subprocess replicas queue
        #: locally and the router pulls (pull_evictions) — either way
        #: the harness's / the daemon's single EvictionExecutor drains
        #: every shard's rollback and preemption victims here
        self.pending_evictions: deque[str] = deque()
        self.replicas: list[PlannerReplica] = []
        self._replica_cfgs: list[TpuKubeConfig] = []
        fake_clock = hasattr(self.clock, "advance")
        for i in range(n):
            rcfg = config
            if n > 1 and config.journal_enabled:
                # per-replica journal segment: each shard's WAL +
                # checkpoints cover exactly its own slice partition
                rcfg = dc_replace(
                    config, journal_path=f"{config.journal_path}.r{i}"
                )
            self._replica_cfgs.append(rcfg)
            self.replicas.append(PlannerReplica(
                i, self._make_transport(i, rcfg, fake_clock)
            ))
        self._n = n
        # fan-out pool for the subprocess mode: calls to DISTINCT
        # replicas run concurrently (one planner process per core —
        # the multi-core speedup); each replica's own connection lock
        # keeps its binds/prepares ordered. None in-process: the
        # in-process replicas share one GIL, so a pool would only add
        # switch overhead to the deterministic tier-1 path.
        self._pool = (ThreadPoolExecutor(
            max_workers=n, thread_name_prefix="tpukube-shard-fanout",
        ) if self.mode == "subprocess" else None)
        self._inflight = 0
        self.health_checks_total = 0
        self.health_failures_total = 0
        self._health_checked_at: Optional[float] = None
        # replica indices whose DrainCoordinator is actively draining
        # (ISSUE 19): a replica mid-drain is legitimately slow — its
        # decision lock is busy migrating residents — and the health
        # checker must NOT dead-mark it (dead-marking aborts its
        # rendezvous parts and rebuilds state the drain is about to
        # retire anyway). The drain registers intent BEFORE its first
        # eviction tick and clears it when no drain remains active.
        self._drain_intent: set[int] = set()
        self.health_skips_draining_total = 0
        # pod key -> last bound AllocResult, decoded from bind answers
        # (subprocess mode only): lets the federated allocation() serve
        # the lifecycle loop's per-release existence checks without an
        # HTTP read per pod. Advisory — the divergence checkers read
        # allocations() straight from the replicas.
        self._alloc_cache: dict[str, AllocResult] = {}
        # N=1 parity gate: every entry point delegates VERBATIM to the
        # sole replica's Extender (same objects, same code path). Only
        # the in-process transport has an extender in this process —
        # an N=1 SUBPROCESS router routes normally, over the wire.
        self._sole = (self.replicas[0].extender
                      if n == 1 and self.mode == "inprocess" else None)
        # wire each in-process replica's drain choreography to the
        # router's intent set (subprocess replicas drain behind their
        # own listener; the daemon has no router to shield it, and
        # the health checker there probes /healthz, not the decision
        # lock). No drain (the flag default) wires nothing.
        for rep in self.replicas:
            _ext = rep.extender
            if _ext is not None and getattr(_ext, "drain", None) is not None:
                _ext.drain.attach_router(self, rep.index)
        # router maps only (replica state lives behind each replica's
        # own locks; this leaf lock never nests around them on the
        # mutation path — routing reads replica state lock-free
        # through the epoch-cached snapshots)
        self._lock = threading.RLock()
        self._slice_replica: dict[str, int] = {}
        self._node_replica: dict[str, int] = {}
        self._pod_replica: dict[str, int] = {}
        self._gang_replica: dict[tuple[str, str], int] = {}
        self._dcn: dict[tuple[str, str], _Rendezvous] = {}
        # driver-admitted pods whose owner replica found them
        # unschedulable: attempt counts rotate the next admit to the
        # following replica (the webhook path spills over inline; the
        # admit path has no answer to spill on). Entries retire at
        # bind/release.
        self._pod_attempts: dict[str, int] = {}
        # last scheduling-clock instant the rendezvous janitor ran
        # from the gang-routing path (throttle; see _route_gang)
        self._swept_at: Optional[float] = None
        # rendezvous aborted while participants were unreachable:
        # key -> the replica indices that could NOT be dissolved at
        # abort time. A healed/restarted participant still on the list
        # has its leftover fragment dissolved (even a locally-committed
        # one — death is all-or-nothing), then leaves the list; the
        # key retires when the list empties. Scoping the sentence to
        # the EXACT unreachable replicas means a same-named gang
        # re-created meanwhile on other replicas is never touched.
        self._aborted_dcn: dict[tuple[str, str], set[int]] = {}
        # what path the last restart_replica took ({"replica", "warm",
        # "restored"}; None before any restart): warm=True means the
        # replica's own journal segment replayed (ROADMAP sharding
        # item (d)), warm=False on a journal-enabled replica means the
        # recovery failure ladder fell back to the cold re-ingest
        self.last_restart: Optional[dict] = None
        # replica index -> (clock instant, gauges): the subprocess
        # routing pre-filter's per-instant memo (see _gauges_of)
        self._gauge_cache: dict[int, tuple[float, dict]] = {}
        # (replica, gang key) -> (clock instant, fit/reservation
        # answer): the subprocess gang-routing memo. A 512-member gang
        # admitted in one burst (one clock instant) must not pay one
        # fit probe + one reservation read PER MEMBER over the wire;
        # staleness within an instant only defers a gang one retry —
        # the reservation itself is taken under the replica's locks.
        self._fit_cache: dict[tuple[int, tuple[str, str]],
                              tuple[float, bool]] = {}
        self._rsv_cache: dict[tuple[int, tuple[str, str]],
                              tuple[float, Optional[dict]]] = {}
        # counters (per-replica metrics/statusz)
        self.rendezvous_prepared = 0
        self.rendezvous_committed = 0
        self.rendezvous_aborted = 0
        self.state = _FederatedState(self)
        self.cycle = (_RouterCycle(self)
                      if config.batch_enabled else None)
        self.events = _MergedEvents(self)
        self.journal = None
        # -- federated observability plane (ISSUE 16) -------------------
        # Router-local trace spans (fan-out timing), route/spillover/
        # rendezvous provenance, and the fan-out flight recorder exist
        # ONLY when the router actually federates (N>1, or any
        # subprocess topology). The N=1 in-process parity gate keeps
        # the router invisible: trace/decisions stay None and the sole
        # Extender's own surfaces serve verbatim (off-is-off — the
        # byte-compat goldens hold).
        self._trace_ids = None
        self._flights: Optional[deque] = None
        if self._sole is not None:
            self.trace = None
            self.decisions = None
        else:
            import itertools

            self._trace_ids = itertools.count(1)
            self.trace = (trace_mod.DecisionTrace(
                capacity=config.trace_capacity,
                path=(f"{config.trace_path}.router"
                      if config.trace_path else None),
                max_sink_bytes=config.trace_sink_max_bytes,
            ) if config.trace_capacity > 0 else None)
            from tpukube.obs.decisions import DecisionLog

            self.decisions = (DecisionLog(
                capacity=config.decisions_capacity,
                sample_rate=config.decisions_sample_rate,
                seed=config.decisions_seed,
                path=(f"{config.decisions_path}.router"
                      if config.decisions_path else None),
                max_sink_bytes=config.decisions_sink_max_bytes,
            ) if config.decisions_enabled else None)
            # bounded ring of recent fan-out requests with sizes and
            # RTTs (/statusz "flights" section) — fed by the subprocess
            # transports' on_wire hook; stays empty in-process
            self._flights = deque(maxlen=256)
            for rep in self.replicas:
                if rep.transport.mode == "subprocess":
                    rep.transport.on_wire = self._record_flight

    def _make_transport(self, index: int, rcfg: TpuKubeConfig,
                        fake_clock: bool):
        if self.mode == "subprocess":
            return SubprocessTransport(
                index, rcfg, fake_clock=fake_clock,
                on_down=self._on_transport_down,
            )
        return InProcessTransport(Extender(
            rcfg, clock=self.clock,
            eviction_sink=self.pending_evictions,
        ))

    def _on_transport_down(self, idx: int) -> None:
        """A transport-level connection failure: the daemon is gone (or
        unreachable) mid-call. Mark the replica dead with the SAME
        semantics as ``crash_replica`` — excluded from the federated
        views, rendezvous parts treated as lost by the janitor, warm
        restart via ``restart_replica``."""
        rep = self.replicas[idx]
        if rep.alive or not rep.killed:
            rep.alive = False
            rep.killed = True
            self._drop_dead_alloc_cache(idx)
            log.error("replica %s marked dead (transport failure)",
                      rep.name)

    def _drop_dead_alloc_cache(self, idx: int) -> None:
        """Purge the dead replica's entries from the bind-answer alloc
        cache: the federated ``allocation()`` must stop serving the
        corpse's ledger the moment ``allocations()`` does (the
        dead-shard invariant the chaos checkers assert). Restart
        re-primes the survivors from the pod annotations."""
        with self._lock:
            dead = [k for k, i in self._pod_replica.items()
                    if i == idx]
            for key in dead:
                self._alloc_cache.pop(key, None)

    def _fan_out(self, reps: list[PlannerReplica], fn) -> dict[int, Any]:
        """Run ``fn(rep)`` for each replica — concurrently in
        subprocess mode (the multi-core fan-out), serially in-process
        (one GIL; a pool would only reorder the deterministic tier-1
        path). A replica that dies mid-call is skipped; its death is
        already recorded by the transport's ``on_down``."""
        out: dict[int, Any] = {}
        if self._pool is not None and len(reps) > 1:
            ctx = trace_mod.TRACE_CONTEXT.get()
            if ctx is not None:
                # ThreadPoolExecutor does not propagate contextvars:
                # re-set the trace context inside each pooled call so
                # the transport stamps the X-Tpukube-Trace header
                inner = fn

                def fn(rep, _inner=inner, _ctx=ctx):
                    tok = trace_mod.TRACE_CONTEXT.set(_ctx)
                    try:
                        return _inner(rep)
                    finally:
                        trace_mod.TRACE_CONTEXT.reset(tok)
            with self._lock:
                self._inflight += 1
            try:
                futures = {rep.index: self._pool.submit(fn, rep)
                           for rep in reps}
                for idx, fut in futures.items():
                    try:
                        out[idx] = fut.result()
                    except ReplicaUnavailable:
                        continue
            finally:
                with self._lock:
                    self._inflight -= 1
            return out
        for rep in reps:
            try:
                out[rep.index] = fn(rep)
            except ReplicaUnavailable:
                continue
        return out

    # -- federated observability helpers ------------------------------------
    def _traced(self, op: str, pod_key: str = "", **fields):
        """Context manager around one fanned operation: allocates a
        trace id, exposes it through ``TRACE_CONTEXT`` (the transport
        stamps it on every request it carries; the workers tag their
        records with it), and records one router span with explicit
        wall-clock bounds on exit — the enclosing slice the merged
        timeline nests worker spans under. A no-op object when router
        tracing is off (N=1 in-process, or trace_capacity 0)."""
        from contextlib import contextmanager

        @contextmanager
        def _span():
            if self.trace is None or self._trace_ids is None:
                yield
                return
            cur = trace_mod.TRACE_CONTEXT.get()
            if cur is not None:
                # nested fan-out (e.g. the sweep inside a gang route):
                # stay on the enclosing trace, allocate a child span
                tid = cur["trace"]
                sid = f"{tid}.{next(self._trace_ids)}"
            else:
                tid = f"t{next(self._trace_ids)}"
                sid = f"{tid}.0"
            tok = trace_mod.TRACE_CONTEXT.set(
                {"trace": tid, "parent": sid})
            t0 = time.time()
            try:
                yield
            finally:
                trace_mod.TRACE_CONTEXT.reset(tok)
                self.trace.span(op, pod_key, trace=tid, span=sid,
                                t0=t0, t1=time.time(), **fields)

        return _span()

    def _decide(self, pod_key: str, stage: str, **fields) -> None:
        """Record one router-side provenance stage (route / spillover /
        rendezvous) when provenance is on and the pod is sampled."""
        dlog = self.decisions
        if dlog is not None and dlog.wants(pod_key):
            dlog.record(pod_key, stage, replica_source="router",
                        **fields)

    def _record_flight(self, idx: int, op: str, tx: int, rx: int,
                       dt: float, codec_used: str = "json") -> None:
        """The subprocess transports' on_wire hook: one bounded ring
        entry per completed request (sizes + RTT) — the /statusz
        flight recorder. Lock-free (one atomic deque append)."""
        flights = self._flights
        if flights is not None:
            entry = {
                "ts": round(time.time(), 3),
                "replica": f"r{idx}",
                "op": op,
                "tx_bytes": tx,
                "rx_bytes": rx,
                "rtt_ms": round(dt * 1000.0, 3),
            }
            if codec_used != "json":
                # tagged only off the JSON default, so the recorder's
                # entry shape is unchanged on the oracle path
                entry["codec"] = codec_used
            flights.append(entry)

    def flights_snapshot(self, limit: int = 64) -> list[dict[str, Any]]:
        """Most recent fan-out requests, oldest first."""
        if self._flights is None:
            return []
        for _ in range(5):
            try:
                out = list(self._flights)
                break
            except RuntimeError:  # deque mutated mid-iteration
                continue
        else:
            out = []
        return out[-limit:]

    def wire_totals(self) -> dict[str, Any]:
        """Cumulative wire bytes across every replica transport (zeros
        in-process — direct dispatch moves no bytes): the bytes-per-
        churn-wave numerator on the driver surface, and the baseline
        the ROADMAP codec item is judged against."""
        tx = rx = 0
        raw_tx = raw_rx = 0
        codec_name = None
        by_op: dict[str, dict[str, int]] = {}
        per_replica: dict[str, dict[str, int]] = {}
        for rep in self.replicas:
            snap = rep.transport.wire_snapshot() \
                if hasattr(rep.transport, "wire_snapshot") else None
            if not snap:
                continue
            tx += snap["tx"]
            rx += snap["rx"]
            per_replica[rep.name] = {"tx": snap["tx"], "rx": snap["rx"]}
            if "codec" in snap:
                codec_name = snap["codec"]
                raw_tx += snap["raw_tx"]
                raw_rx += snap["raw_rx"]
            for op, cell in snap["by_op"].items():
                agg = by_op.setdefault(
                    op, {"tx": 0, "rx": 0, "calls": 0})
                for k in ("tx", "rx", "calls"):
                    agg[k] += cell[k]
                # codec-tagged cells carry failures/raw counters; fold
                # them in without changing the default cell shape
                if "failures" in cell:
                    agg["failures"] = \
                        agg.get("failures", 0) + cell["failures"]
                if "codec" in cell:
                    agg["codec"] = cell["codec"]
                    agg["raw_tx"] = \
                        agg.get("raw_tx", 0) + cell.get("raw_tx", 0)
                    agg["raw_rx"] = \
                        agg.get("raw_rx", 0) + cell.get("raw_rx", 0)
        doc = {"tx": tx, "rx": rx, "total": tx + rx,
               "per_replica": per_replica, "by_op": by_op}
        if codec_name is not None:
            # bytes the codec kept off the wire and the resulting
            # compression ratio (pre-compression frames / wire bytes)
            doc["codec"] = codec_name
            doc["raw_tx"] = raw_tx
            doc["raw_rx"] = raw_rx
            doc["saved"] = max(0, (raw_tx + raw_rx) - (tx + rx))
            wire_total = tx + rx
            doc["ratio"] = (round((raw_tx + raw_rx) / wire_total, 3)
                            if wire_total else None)
        return doc

    def explain(self, pod_key: str) -> Optional[dict[str, Any]]:
        """Stitched federated /explain: the router's own route /
        spillover / rendezvous stages (including the gang pseudo-key
        chain when the pod belongs to a DCN gang) merged with every
        alive replica's local chain for the pod, rendered as ONE
        document — a DCN gang member's explain names both replicas and
        the rendezvous verdict. N=1 delegates to the sole planner's
        log verbatim (off-is-off)."""
        from tpukube.obs.decisions import explain_doc, merge_stage_events

        if self._sole is not None:
            dlog = self._sole.decisions
            return dlog.explain(pod_key) if dlog is not None else None
        if "/" not in pod_key:
            pod_key = f"default/{pod_key}"
        groups: list[tuple[str, list[dict[str, Any]]]] = []
        if self.decisions is not None:
            router_evs = [dict(ev)
                          for ev in self.decisions.events(pod=pod_key)]
            # the gang's own rendezvous chain lives under its
            # pseudo-key (gang:<ns>/<name>) so EVERY member can pull
            # it — re-key those events onto the asked pod
            gangs = sorted({ev["gang"] for ev in router_evs
                            if ev.get("gang")})
            for gang in gangs:
                for ev in self.decisions.events(pod=f"gang:{gang}"):
                    ev = dict(ev)
                    ev["pod"] = pod_key
                    router_evs.append(ev)
            if router_evs:
                groups.append(("router", router_evs))
        fanned = self._fan_out(
            self._alive(), lambda rep: rep.transport.explain(pod_key)
        )
        for idx in sorted(fanned):
            doc = fanned[idx]
            if doc and doc.get("stages"):
                groups.append(
                    (self.replicas[idx].name, doc["stages"]))
        if not groups:
            return None
        return explain_doc(merge_stage_events(groups), pod_key)

    def events_federated(self, reason=None, pod=None, node=None,
                         since=None, replica=None,
                         limit: Optional[int] = None
                         ) -> list[dict[str, Any]]:
        """Merged event journals across the replica set, every event
        stamped with its source replica, wall-clock ordered — the
        router /events surface and `tpukube-obs events --replica`
        feed."""
        rows: list[dict[str, Any]] = []
        fanned = self._fan_out(
            self._alive(),
            lambda rep: rep.transport.events_query(
                reason=reason, pod=pod, node=node, since=since),
        )
        for idx in sorted(fanned):
            name = self.replicas[idx].name
            for ev in fanned[idx] or []:
                if not isinstance(ev, dict):
                    continue
                ev = dict(ev)
                ev.setdefault("replica", name)
                rows.append(ev)
        if replica is not None:
            rows = [e for e in rows if e.get("replica") == replica]
        rows.sort(key=lambda e: (float(e.get("ts", 0.0)),
                                 str(e.get("replica", ""))))
        if limit is not None:
            rows = rows[-limit:]
        return rows

    def capacity_doc(self, since=None) -> Optional[dict[str, Any]]:
        """The router /capacity surface: N=1 serves the sole planner's
        document verbatim (off-is-off); N>1 stitches EVERY replica's
        answer — a killed or unreachable replica lands in
        ``dead_replicas`` so the merged fleet view degrades loudly
        instead of silently narrowing (never stale, never partial
        without saying so). None when no replica has capacity on."""
        from tpukube.obs.capacity import merge_capacity_docs

        if self._sole is not None:
            cap = self._sole.capacity
            return cap.capacity_doc(since=since) if cap is not None \
                else None
        fanned = self._fan_out(
            self._alive(),
            lambda rep: rep.transport.capacity_doc(since=since),
        )
        per: list[tuple[str, Optional[dict[str, Any]]]] = []
        for rep in self.replicas:
            per.append((rep.name, fanned.get(rep.index)))
        if not any(doc is not None for _, doc in per):
            return None
        return merge_capacity_docs(per)

    def capacity_probe(self, count=None, shape=None,
                       chips_per_pod=1) -> Optional[dict[str, Any]]:
        """The router /capacity/probe surface: fans the read-only
        what-if ask to every replica and merges — the demand fits if
        ANY replica fits it whole; the DCN fallback composes the
        per-replica largest boxes; dead replicas are named in the
        answer (a probe that cannot see a shard must say so)."""
        from tpukube.obs.capacity import merge_probe_docs

        if self._sole is not None:
            cap = self._sole.capacity
            if cap is None:
                return None
            return cap.probe(count=count, shape=shape,
                             chips_per_pod=chips_per_pod)
        fanned = self._fan_out(
            self._alive(),
            lambda rep: rep.transport.capacity_probe(
                count=count, shape=shape, chips_per_pod=chips_per_pod),
        )
        per = [(rep.name, fanned.get(rep.index))
               for rep in self.replicas]
        if not any(doc is not None for _, doc in per):
            return None
        total = (count if count is not None
                 else shape[0] * shape[1] * shape[2])
        return merge_probe_docs(per, {
            "count": count,
            "shape": list(shape) if shape else None,
            "chips": total,
        })

    # -- Extender-surface passthroughs --------------------------------------
    @property
    def evict_precheck(self):
        ext = self.replicas[0].extender
        return ext.evict_precheck if ext is not None else None

    @evict_precheck.setter
    def evict_precheck(self, fn) -> None:
        for rep in self.replicas:
            rep.transport.set_evict_precheck(fn)

    @property
    def binder(self):
        ext = self.replicas[0].extender
        return ext.binder if ext is not None else None

    @binder.setter
    def binder(self, fn) -> None:
        for rep in self.replicas:
            rep.transport.set_binder(fn)

    @property
    def degraded_gate(self):
        ext = self.replicas[0].extender
        return ext.degraded_gate if ext is not None else None

    @degraded_gate.setter
    def degraded_gate(self, fn) -> None:
        for rep in self.replicas:
            rep.transport.set_degraded_gate(fn)

    @property
    def latencies(self) -> dict[str, list[float]]:
        """Merged webhook-latency windows (quantile feeds)."""
        out: dict[str, list[float]] = {}
        for rep in self.replicas:
            if rep.killed:
                continue
            try:
                windows = rep.transport.latencies()
            except ReplicaUnavailable:
                continue
            for handler, window in windows.items():
                out.setdefault(handler, []).extend(window)
        return out

    def _summed(self, field: str) -> int:
        total = 0
        for rep in self.replicas:
            if rep.killed:
                continue
            try:
                total += rep.transport.summary()[field]
            except ReplicaUnavailable:
                continue
        return total

    @property
    def preemptions(self) -> int:
        return self._summed("preemptions")

    @property
    def binds_total(self) -> int:
        return self._summed("binds_total")

    def gang_snapshot(self) -> list[dict[str, Any]]:
        out: list[dict[str, Any]] = []
        for rep in self.replicas:
            if rep.killed:
                continue  # a dead shard's reservations died with it
            try:
                out.extend(rep.transport.gang_snapshot())
            except ReplicaUnavailable:
                continue
        return sorted(out, key=lambda g: (g["namespace"], g["group"]))

    def alloc_snapshot(self) -> list[dict[str, Any]]:
        out: list[dict[str, Any]] = []
        for rep in self.replicas:
            if rep.killed:
                continue
            try:
                out.extend(rep.transport.alloc_snapshot())
            except ReplicaUnavailable:
                continue
        return sorted(out, key=lambda a: a["pod"])

    def audit_stats(self) -> dict[str, Any]:
        """Summed snapshot-audit sentinel counters across replicas."""
        rows = []
        for rep in self.replicas:
            if rep.killed:
                continue
            try:
                rows.append(rep.transport.summary()["audit"])
            except ReplicaUnavailable:
                continue
        return {
            "rate": max((r["rate"] for r in rows), default=0.0),
            "checks": sum(r["checks"] for r in rows),
            "divergences": sum(r["divergences"] for r in rows),
        }

    def statusz(self) -> dict[str, Any]:
        """The router's /statusz section: topology + rendezvous state +
        one summary row per replica (the per-replica observability leg
        of the sharded plane; each replica's full extender_statusz
        stays available on its own listener in a real deployment)."""
        with self._lock:
            rendezvous = [
                {
                    "gang": f"{key[0]}/{key[1]}",
                    "committed": rdv.committed,
                    "parts": {
                        self.replicas[idx].name: {
                            sid: len(coords)
                            for sid, coords in parts.items()
                        }
                        for idx, parts in rdv.parts.items()
                    },
                }
                for key, rdv in sorted(self._dcn.items())
            ]
            slice_map = {
                sid: self.replicas[idx].name
                for sid, idx in sorted(self._slice_replica.items())
            }
        per_replica = []
        for rep in self.replicas:
            row = {
                "replica": rep.name,
                "alive": rep.alive,
                "pods_routed": rep.pods_routed,
            }
            summary = None
            if not rep.killed:
                try:
                    summary = rep.transport.summary()
                except ReplicaUnavailable:
                    summary = None
            if summary is None:
                # a dead daemon's ledger died with it: render the row
                # with liveness only, exactly what an operator sees
                row.update({"slices": [], "nodes": 0, "allocs": 0,
                            "binds_total": 0, "utilization": 0.0,
                            "queue_depth": 0, "snapshot_hits": 0,
                            "snapshot_rebuilds": 0})
            else:
                row.update({
                    "slices": summary["slices"],
                    "nodes": summary["nodes"],
                    "allocs": summary["allocs"],
                    "binds_total": summary["binds_total"],
                    "utilization": round(summary["utilization"], 4),
                    "queue_depth": summary["queue_depth"],
                    "snapshot_hits": summary["snapshot_hits"],
                    "snapshot_rebuilds": summary["snapshot_rebuilds"],
                })
                if "lock_graph" in summary:
                    # federated lockgraph (ISSUE 18): the worker's
                    # observed lock-order edges ride its status row
                    # when the monitor is live (key absent otherwise —
                    # off-is-off)
                    row["lock_graph"] = summary["lock_graph"]
            if self._sole is None and summary is not None:
                # federated per-replica observability sections: each
                # worker's decisions ring / event journal / journal
                # stats, attributed by replica (a dead daemon's row
                # stays liveness-only above)
                try:
                    zdoc = rep.transport.statusz_doc()
                except (ReplicaUnavailable, ShardError):
                    zdoc = None
                if zdoc is not None:
                    row["decisions"] = zdoc.get("decisions")
                    row["events"] = zdoc.get("events")
                    row["journal"] = zdoc.get("journal")
            per_replica.append(row)
        doc = {
            "replicas": per_replica,
            "slice_assignment": slice_map,
            "rendezvous": {
                "live": rendezvous,
                "prepared": self.rendezvous_prepared,
                "committed": self.rendezvous_committed,
                "aborted": self.rendezvous_aborted,
            },
            "transport": self.transport_statusz(),
        }
        with self._lock:
            intent = sorted(self._drain_intent)
            skips = self.health_skips_draining_total
        if intent:
            # present only while a drain shields replicas (off-is-off:
            # no drain, no key — the statusz goldens hold byte-for-byte)
            doc["drain_intent"] = [self.replicas[i].name for i in intent]
        if intent or skips:
            # the drain/health-check race fix's receipt: probes skipped
            # because the replica was shielded by drain intent (can only
            # be nonzero with the drain flag on, so off stays off)
            doc["health_skips_draining_total"] = skips
        if self._sole is None:
            # the router's OWN observability plane (absent under the
            # N=1 in-process parity gate — off-is-off)
            doc["router_obs"] = {
                "trace": (self.trace.stats() if self.trace is not None
                          else {"enabled": False}),
                "decisions": (self.decisions.stats()
                              if self.decisions is not None
                              else {"enabled": False}),
            }
            doc["wire"] = self.wire_totals()
            doc["flights"] = self.flights_snapshot()
        return doc

    def transport_statusz(self) -> dict[str, Any]:
        """The router's transport section: mode, in-flight fan-outs,
        and per-replica link liveness/RTT — the observability leg the
        process mode adds (satellite of ISSUE 14). In-process mode
        reports the mode alone: there is no wire to measure."""
        from tpukube.obs.registry import quantile

        out: dict[str, Any] = {"mode": self.mode}
        if self.mode != "subprocess":
            return out
        with self._lock:
            out["in_flight_fanouts"] = self._inflight
        out["health_checks"] = self.health_checks_total
        out["health_failures"] = self.health_failures_total
        rows = []
        for rep in self.replicas:
            tr = rep.transport
            rtts = tr.rtt_snapshot()
            wire = tr.wire_snapshot()
            rows.append({
                "replica": rep.name,
                "alive": rep.alive,
                "rtt_p50_ms": round(1000 * quantile(rtts, 0.5), 3),
                "rtt_p99_ms": round(1000 * quantile(rtts, 0.99), 3),
                "requests": tr.rtt_count,
                "health_checks": tr.health_checks,
                "health_failures": tr.health_failures,
                "wire_tx_bytes": wire["tx"] if wire else 0,
                "wire_rx_bytes": wire["rx"] if wire else 0,
            })
        out["replicas"] = rows
        return out

    # -- slice / node / pod assignment --------------------------------------
    def _slice_of_payload(self, annotations: dict[str, str]) -> Optional[str]:
        payload = annotations.get(codec.ANNO_NODE_TOPOLOGY)
        if not payload:
            return None
        try:
            obj = json.loads(payload)
        except (TypeError, ValueError):
            return None
        sid = obj.get("slice")
        return sid if isinstance(sid, str) and sid else None

    def _assign_slice_locked(self, sid: str) -> int:
        """Deterministic least-loaded slice→replica assignment: a new
        slice goes to the replica owning the fewest slices (ties break
        on index), so a fleet whose slices register in sorted order —
        the sim and any annotation-synced cluster — balances exactly.
        Recorded in the router map; a production deployment pins the
        same assignment in per-replica config."""
        idx = self._slice_replica.get(sid)
        if idx is None:
            counts = [0] * self._n
            for i in self._slice_replica.values():
                counts[i] += 1
            idx = min(range(self._n), key=lambda i: (counts[i], i))
            self._slice_replica[sid] = idx
            log.info("slice %s assigned to replica %s", sid,
                     self.replicas[idx].name)
        return idx

    def _replica_for_node(
        self, name: str, annotations: Optional[dict[str, str]] = None
    ) -> Optional[int]:
        with self._lock:
            idx = self._node_replica.get(name)
            if idx is not None:
                return idx
            if annotations is None:
                return None
            sid = self._slice_of_payload(annotations)
            if sid is None:
                return None
            idx = self._assign_slice_locked(sid)
            self._node_replica[name] = idx
            return idx

    def _alive(self) -> list[PlannerReplica]:
        return [r for r in self.replicas if r.alive]

    def _hash_replica(self, pod_key: str) -> int:
        return zlib.crc32(pod_key.encode("utf-8")) % self._n

    def _pick_pod_replica(self, pod_key: str,
                          attempts: Optional[int] = None) -> int:
        """Stable hash with liveness fallback: the hash spreads the
        burst plane uniformly; a dead primary falls over to the next
        alive index. Spillover on a FULL primary: the webhook path
        retries the other replicas inline (filter answers), the admit
        path rotates by the pod's recorded failed-plan attempts
        (pass ``attempts`` pre-read to save a lock round-trip on the
        driver hot path — there is ONE rotation policy, not two)."""
        if attempts is None:
            with self._lock:
                attempts = self._pod_attempts.get(pod_key, 0)
        primary = self._hash_replica(pod_key) + attempts
        for off in range(self._n):
            idx = (primary + off) % self._n
            if self.replicas[idx].alive:
                return idx
        raise ShardError("no alive planner replica")

    # -- node partitioning for webhook bodies --------------------------------
    def _partition_nodes(
        self, nodes: list[dict[str, Any]]
    ) -> dict[int, list[dict[str, Any]]]:
        """Split a raw-node webhook body per owning replica (unknown
        names — nodes never annotated — are dropped from every part).
        Only the RAW mode partitions: a replica must never ingest
        another shard's node objects. Names-only bodies forward
        verbatim — the target replica answers its own nodes and
        reports the rest infeasible, which is both correct and O(1)
        under plan-served filter answers (re-partitioning 10k names
        per webhook was a measured router tax)."""
        parts: dict[int, list[dict[str, Any]]] = {}
        for obj in nodes:
            name, annotations = kube.node_name_and_annotations(obj)
            idx = self._replica_for_node(name, annotations)
            if idx is None:
                continue
            parts.setdefault(idx, []).append(obj)
        return parts

    # -- gang routing + two-phase rendezvous ---------------------------------
    def _gang_chips(self, pod: PodInfo) -> Optional[tuple[int, int]]:
        """(chips_per_pod, total chips) for a gang pod, None when the
        request is malformed (the home replica reports the schema
        error exactly as the unsharded path would)."""
        try:
            ask = Extender.device_request(pod)
        except ExtenderError:
            return None  # the routed replica reports the schema error
        if ask is None or pod.group is None:
            return None
        return ask[1], ask[1] * pod.group.min_member

    def _gauges_of(self, rep: PlannerReplica) -> dict[str, dict]:
        """The replica's per-slice capacity gauges. In-process: a
        direct cached-snapshot read (O(slices), free). Subprocess: one
        GET, memoized per scheduling-clock instant — a 512-member gang
        admitted in one batch must not pay 512xN gauge round-trips;
        the full fit probe stays authoritative, so gauge staleness
        within one instant can only defer a gang one retry."""
        if rep.transport.mode == "inprocess":
            return rep.transport.gauges()
        now = self.clock.monotonic()
        with self._lock:
            ent = self._gauge_cache.get(rep.index)
            if ent is not None and ent[0] == now:
                return ent[1]
        gauges = rep.transport.gauges()
        with self._lock:
            self._gauge_cache[rep.index] = (now, gauges)
        return gauges

    def _replica_fits_gang(self, rep: PlannerReplica, pod: PodInfo,
                           total: int) -> bool:
        """Can this replica host the gang ICI-contiguously in ONE of
        its slices? The cheap largest-free-box gauge (cached on the
        replica's snapshot) pre-filters: it can only over-estimate the
        blocked sweep's contiguity, so a replica it rejects cannot fit
        the gang and the full probe — a sweep, and in process mode a
        round-trip — never runs there. The probe itself is the same
        search ``ensure_reservation`` runs, against the replica's
        epoch-cached snapshot."""
        key = (pod.namespace,
               pod.group.name if pod.group is not None else pod.name)
        if rep.transport.mode == "subprocess":
            now = self.clock.monotonic()
            with self._lock:
                ent = self._fit_cache.get((rep.index, key))
            if ent is not None and ent[0] == now:
                return ent[1]
        try:
            gauges = self._gauges_of(rep)
            if all(g["largest_free_box"] < total
                   for g in gauges.values()):
                fits = False
            else:
                fits = rep.transport.gang_fit(pod, total)
        except ReplicaUnavailable:
            return False
        if rep.transport.mode == "subprocess":
            with self._lock:
                self._fit_cache[(rep.index, key)] = (now, fits)
        return fits

    def _route_gang(self, pod: PodInfo) -> int:
        """The gang pod's target replica: its rendezvous participant
        with room, its established home, or — for a new gang — the
        first replica that fits it whole; a gang that fits nowhere and
        opted into DCN gets the two-phase rendezvous. Falls back to
        the emptiest alive replica so error answers (config mistakes,
        genuinely unschedulable gangs) come from a deterministic
        place."""
        assert pod.group is not None
        key = (pod.namespace, pod.group.name)
        # the janitor runs at most once per scheduling-clock instant:
        # a 512-member gang admitted in one batch (one FakeClock tick,
        # one webhook burst) must not pay 512 full rendezvous sweeps —
        # plan_pending() additionally sweeps once per drive
        now = self.clock.monotonic()
        if now != self._swept_at:
            self._swept_at = now
            with self._lock:
                # the per-instant routing memos expire with the instant
                self._fit_cache.clear()
                self._rsv_cache.clear()
                self._gauge_cache.clear()
            self.sweep()
        with self._lock:
            rdv = self._dcn.get(key)
        if rdv is not None:
            idx = self._rendezvous_member_target(rdv, pod)
            if idx is not None:
                return idx
            # every part full: overflow replica — any participant
            # answers it as a normal pod (assignable() is False there)
            for idx in rdv.parts:
                if self.replicas[idx].alive:
                    return idx
        with self._lock:
            home = self._gang_replica.get(key)
        if home is not None and self.replicas[home].alive \
                and self._reservation_routed(self.replicas[home],
                                             key) is not None:
            # sticky only while the home actually HOLDS a reservation:
            # a gang that transiently fit nowhere must re-probe the
            # whole fleet (and the rendezvous) on every retry, not
            # stay pinned to whichever replica owned the error answer
            return home
        ask = self._gang_chips(pod)
        ranked = sorted(
            self._alive(),
            key=lambda r: (self.state_utilization_of(r), r.index),
        )
        if not ranked:
            raise ShardError("no alive planner replica")
        if home is not None and self.replicas[home].alive:
            # prefer the previous home when it still fits — re-probing
            # must not flip a mid-reserve gang between replicas
            ranked.sort(key=lambda r: r.index != home)
        if ask is not None:
            cpp, total = ask
            for rep in ranked:
                if self._replica_fits_gang(rep, pod, total):
                    with self._lock:
                        self._gang_replica[key] = rep.index
                        # the pick is about to consume capacity there:
                        # the NEXT gang routed within this clock
                        # instant must rank against fresh gauges, not
                        # this pick's pre-image
                        self._gauge_cache.pop(rep.index, None)
                    return rep.index
            if pod.group.allow_dcn and pod.group.shape is None \
                    and self._n > 1:
                rdv = self._prepare_rendezvous(pod, cpp, total)
                if rdv is not None:
                    idx = self._rendezvous_member_target(rdv, pod)
                    if idx is not None:
                        return idx
        # nothing fits anywhere (or the request is malformed): the
        # emptiest replica owns the error answer; NOT recorded as a
        # sticky home — the next retry re-probes a changed fleet
        return ranked[0].index

    def state_utilization_of(self, rep: PlannerReplica) -> float:
        """One replica's used-share fraction off its cached snapshot
        gauges (O(slices) — never a ledger walk, and in process mode
        at most one round-trip per clock instant)."""
        try:
            gauges = self._gauges_of(rep)
        except ReplicaUnavailable:
            return 1.0  # unreachable sorts last in emptiest-first orders
        used = sum(g["used_shares"] for g in gauges.values())
        total = sum(g["total_shares"] for g in gauges.values())
        return used / total if total else 0.0

    def _reservation_of(self, rep: PlannerReplica,
                        key: tuple[str, str]) -> Optional[dict]:
        """The replica's reservation record for a gang key (None when
        absent OR when the replica is unreachable — an unreachable
        replica's reservation is exactly as lost as a crashed one's).
        Always a FRESH read: the janitor and the eager commit check
        must see reservation state as of now, never a routing memo."""
        try:
            return rep.transport.gang_reservation(key)
        except ReplicaUnavailable:
            return None

    def _reservation_routed(self, rep: PlannerReplica,
                            key: tuple[str, str]) -> Optional[dict]:
        """The ROUTING path's reservation read, memoized per scheduling
        clock instant over the wire (see _fit_cache): a gang burst's
        members must not pay one reservation round-trip each. A stale
        None only re-ranks through the (also memoized) fit probe to
        the same home; a stale record re-routes a member one retry
        late — both settle within the next instant."""
        if rep.transport.mode != "subprocess":
            return self._reservation_of(rep, key)
        now = self.clock.monotonic()
        with self._lock:
            ent = self._rsv_cache.get((rep.index, key))
        if ent is not None and ent[0] == now:
            return ent[1]
        res = self._reservation_of(rep, key)
        with self._lock:
            self._rsv_cache[(rep.index, key)] = (now, res)
        return res

    def _rendezvous_member_target(
        self, rdv: _Rendezvous, pod: PodInfo
    ) -> Optional[int]:
        """The participant replica this member filters, scores, AND
        binds on: sticky per pod (every webhook of one member must
        land on the part holding its chips), parts filling in
        replica-index order, each capped at its local quorum — the
        driver path admits every member before any binds, so the
        reservation's own room cannot spread them."""
        with self._lock:
            idx = rdv.member_target.get(pod.key())
            if idx is not None and self.replicas[idx].alive:
                return idx
            routed: dict[int, int] = {}
            for i in rdv.member_target.values():
                routed[i] = routed.get(i, 0) + 1
            for i in sorted(rdv.parts):
                if not self.replicas[i].alive:
                    continue
                if routed.get(i, 0) < rdv.local_min.get(i, 0):
                    rdv.member_target[pod.key()] = i
                    return i
        return None

    def _prepare_rendezvous(
        self, pod: PodInfo, cpp: int, total: int
    ) -> Optional[_Rendezvous]:
        assert pod.group is not None
        key = (pod.namespace, pod.group.name)
        with self._traced("rendezvous_prepare", pod.key(),
                          gang=f"{key[0]}/{key[1]}"):
            return self._prepare_rendezvous_inner(pod, cpp, total)

    def _decide_rendezvous(self, pod_key: str, key: tuple[str, str],
                           **fields) -> None:
        """Record one rendezvous stage on the gang's own pseudo-key
        (``gang:<ns>/<name>``) — the stitched /explain re-keys the gang
        chain into EVERY member's answer, so recording it once covers
        the triggering pod and the members that never touched the
        prepare alike (a per-pod copy would render the verdict twice
        for the trigger). ``pod_key`` stays in the signature as the
        trigger attribution carried on the event itself."""
        if self.decisions is None:
            return
        gang = f"{key[0]}/{key[1]}"
        self.decisions.record(f"gang:{gang}", "rendezvous", gang=gang,
                              replica_source="router",
                              trigger=pod_key or None, **fields)

    @staticmethod
    def _rdv_parts_doc(replicas, parts) -> list[dict[str, Any]]:
        return [
            {"replica": replicas[i].name, "slice": sid,
             "chips": len(coords)}
            for i, p in sorted(parts.items())
            for sid, coords in sorted(p.items())
        ]

    def _prepare_rendezvous_inner(
        self, pod: PodInfo, cpp: int, total: int
    ) -> Optional[_Rendezvous]:
        """Phases 1+2 of the rendezvous (see module docstring): plan
        per-replica contiguous parts greedily, PREPARE each part as a
        local reservation, and commit the rendezvous record — or abort
        every prepared part on the first failure. None = the fleet
        cannot cover the gang; the caller serves the home replica's
        no-slice error and the scheduler retries later."""
        assert pod.group is not None
        key = (pod.namespace, pod.group.name)
        # PLAN: greedy over (replica, slice) by emptiness — one box per
        # slice, each a multiple of chips_per_pod, largest first (the
        # cross-replica mirror of GangManager._plan_dcn_split). The
        # plan reads ONLY the cheap per-replica gauges (largest free
        # box / utilization, cached on each replica's snapshot): no
        # full fit probe — a sweep, and in process mode a round-trip —
        # serializes across N replicas here. The gauge bounds each
        # slice's one-box part; the PREPARE leg re-derives the exact
        # coords on the owning replica and shrinks on races.
        candidates: list[tuple[float, str, int, int]] = []
        for rep in self._alive():
            try:
                gauges = self._gauges_of(rep)
            except ReplicaUnavailable:
                continue
            for sid, g in gauges.items():
                box = (g["largest_free_box"] // cpp) * cpp
                if box >= cpp:
                    candidates.append(
                        (g["utilization"], sid, rep.index, box)
                    )
        candidates.sort(key=lambda c: (c[0], c[1]))
        volumes: dict[int, dict[str, int]] = {}
        remaining = total
        for _, sid, idx, box in candidates:
            if remaining == 0:
                break
            vol = min(remaining, box)
            if vol >= cpp:
                volumes.setdefault(idx, {})[sid] = vol
                remaining -= vol
        if remaining != 0 or len(volumes) < 2:
            # len(volumes) < 2 cannot happen when every single replica
            # already failed the whole-gang fit — defensive: a
            # one-replica "rendezvous" is just that replica's own
            # _plan_dcn_split, which its ensure_reservation will run
            return None
        # PREPARE each part under its replica's own locks (ordered per
        # replica — the transport contract); roll back every prepared
        # part on the first failure or on a gauge-raced shortfall (no
        # members have bound, so drop_reservation — not dissolve — is
        # the abort)
        prepared: list[int] = []
        parts: dict[int, dict[str, list[TopologyCoord]]] = {}
        local_min: dict[int, int] = {}
        got_total = 0
        failure: Optional[str] = None
        for idx in sorted(volumes):
            rep = self.replicas[idx]
            try:
                got = rep.transport.gang_prepare(pod, cpp, volumes[idx])
            except Exception as e:
                # any prepare failure aborts every prepared part; only
                # the EXPECTED races (box re-occupied, slice gone,
                # replica died mid-prepare) degrade to "retry next
                # cycle" — anything else is a bug and re-raises after
                # the abort
                log.warning(
                    "rendezvous %s/%s: prepare on %s failed (%s); "
                    "aborting %d prepared part(s)",
                    key[0], key[1], rep.name, e, len(prepared),
                )
                self._abort_prepared(key, prepared)
                self._decide_rendezvous(
                    pod.key(), key, outcome="aborted",
                    reason=f"prepare failed on {rep.name}")
                if not isinstance(
                    e, (GangError, StateError, ReplicaUnavailable)
                ):
                    raise
                return None
            parts[idx] = got
            members = sum(len(c) for c in got.values()) // cpp
            local_min[idx] = members
            got_total += members * cpp
            prepared.append(idx)
        if got_total != total:
            # a gauge over-estimated and the owning replica came up
            # short: all-or-nothing — drop what was reserved, let the
            # scheduler retry against the changed fleet
            log.warning(
                "rendezvous %s/%s: prepared %d of %d chips (gauges "
                "raced occupancy); aborting", key[0], key[1],
                got_total, total,
            )
            self._abort_prepared(key, prepared)
            self._decide_rendezvous(
                pod.key(), key, outcome="aborted",
                reason="gauges raced occupancy")
            return None
        rdv = _Rendezvous(key, parts, local_min,
                          created=self.clock.monotonic())
        with self._lock:
            self._dcn[key] = rdv
            self.rendezvous_prepared += 1
        self.events.emit(
            "GangReserved", obj=f"gang/{key[0]}/{key[1]}",
            message=(
                f"two-phase rendezvous prepared: {total} chips over "
                f"{sum(len(p) for p in parts.values())} slice part(s) "
                f"on {len(parts)} replica(s)"
            ),
        )
        log.info(
            "rendezvous %s/%s prepared: %d chips over replicas %s",
            key[0], key[1], total,
            {self.replicas[i].name: sorted(p) for i, p in parts.items()},
        )
        self._decide_rendezvous(
            pod.key(), key, outcome="prepared", chips=total,
            parts=self._rdv_parts_doc(self.replicas, parts))
        return rdv

    def _abort_prepared(self, key: tuple[str, str],
                        prepared: list[int]) -> None:
        """Drop every prepared (member-less) part of an aborted
        rendezvous prepare and count the abort."""
        for pidx in prepared:
            try:
                self.replicas[pidx].transport.gang_drop(key)
            except ReplicaUnavailable:
                # the replica died holding a member-less reservation:
                # its TTL janitor (or the restart rebuild, which finds
                # no bound members) retires it — nothing leaks
                continue
        with self._lock:
            self.rendezvous_aborted += 1

    def sweep(self) -> list[tuple[str, str]]:
        """The rendezvous janitor (phase 3's abort half), run at the
        top of every gang routing and every batch drive: sweep each
        participant's local TTL/fault janitor, then enforce
        all-or-nothing — an uncommitted rendezvous that lost ANY part
        (TTL rollback, fault, replica killed/partitioned) dissolves
        its surviving parts, evicting their bound members through the
        shared eviction bus. A COMMITTED rendezvous tolerates a dead
        replica: its part is durable in pod annotations and restores
        with the replica. Returns the aborted gang keys."""
        if self.mode == "subprocess":
            # the process-mode janitor legs: detect dead daemons (a
            # failed health check = crash_replica semantics), run every
            # worker's own gang TTL janitor (in-process replicas sweep
            # inside their webhook handling; a worker daemon between
            # webhooks must be swept from here or an expired
            # reservation would linger until its next request), then
            # pull the replica-local eviction queues — INCLUDING any
            # victims those sweeps just rolled back — onto the shared
            # bus
            with self._traced("sweep"):
                self.health_check()
                self._fan_out(self._alive(),
                              lambda rep: rep.transport.gang_sweep())
                self.pull_evictions()
        aborted: list[tuple[str, str]] = []
        with self._lock:
            live = list(self._dcn.items())
        for key, rdv in live:
            held: list[tuple[int, Any]] = []
            lost = False
            for idx in rdv.parts:
                rep = self.replicas[idx]
                if not rep.alive:
                    if not rdv.committed:
                        lost = True
                    continue
                try:
                    rep.transport.gang_sweep()
                    res = rep.transport.gang_reservation(key)
                except ReplicaUnavailable:
                    # died mid-sweep: same as not alive above
                    if not rdv.committed:
                        lost = True
                    continue
                if res is None:
                    lost = True
                else:
                    held.append((idx, res))
            if not rdv.committed and held and not lost \
                    and all(res["committed"] for _, res in held) \
                    and len(held) == len(rdv.parts):
                self._check_rendezvous_commit(rdv)
                continue
            if lost and not rdv.committed:
                for idx, _res in held:
                    try:
                        self.replicas[idx].transport.gang_dissolve(key)
                    except ReplicaUnavailable:
                        continue  # now unreachable: settled on return
                unreachable = {
                    idx for idx in rdv.parts
                    if not self.replicas[idx].alive
                }
                with self._lock:
                    self._dcn.pop(key, None)
                    self._gang_replica.pop(key, None)
                    if unreachable:
                        self._aborted_dcn.setdefault(
                            key, set()).update(unreachable)
                    self.rendezvous_aborted += 1
                aborted.append(key)
                self.events.emit(
                    "GangRollback", obj=f"gang/{key[0]}/{key[1]}",
                    message=(
                        "rendezvous aborted: a part was lost before "
                        "commit (TTL/fault/replica down); surviving "
                        "parts dissolved all-or-nothing"
                    ), type="Warning",
                )
                log.warning("rendezvous %s/%s aborted (part lost "
                            "pre-commit)", key[0], key[1])
                self._decide_rendezvous(
                    "", key, outcome="aborted",
                    reason="part lost pre-commit")
            elif not held and rdv.committed and all(
                self.replicas[idx].alive for idx in rdv.parts
            ):
                # every part released naturally (members finished):
                # the rendezvous record retires
                with self._lock:
                    self._dcn.pop(key, None)
                    self._gang_replica.pop(key, None)
        # retire gang-home entries whose reservation is gone (the gang
        # completed or rolled back): routing already re-probes on a
        # missing reservation, so this is purely the memory bound —
        # unbounded unique gang names must not grow the map forever
        with self._lock:
            homes = [(k, i) for k, i in self._gang_replica.items()
                     if k not in self._dcn]
        for key, idx in homes:
            rep = self.replicas[idx]
            if rep.alive \
                    and self._reservation_of(rep, key) is None:
                with self._lock:
                    if self._gang_replica.get(key) == idx \
                            and key not in self._dcn:
                        self._gang_replica.pop(key, None)
        return aborted

    # -- process-mode janitors ----------------------------------------------
    def health_check(self) -> int:
        """Health-check the subprocess replica set (throttled to once
        per scheduling-clock instant — sweep() runs this at the top of
        every drive and every gang routing). A replica that fails its
        check is marked DEAD with ``crash_replica`` semantics: routed
        around, excluded from the federated views, its uncommitted
        rendezvous parts aborted by the janitor, warm restart via
        ``restart_replica``. Returns how many replicas failed."""
        if self.mode != "subprocess":
            return 0
        now = self.clock.monotonic()
        with self._lock:
            if self._health_checked_at == now:
                return 0
            self._health_checked_at = now
        failed = 0
        with self._traced("health_check"):
            for rep in self.replicas:
                if not rep.alive:
                    continue
                with self._lock:
                    draining = rep.index in self._drain_intent
                if draining:
                    # drain/health-check race (ISSUE 19): a replica
                    # mid-drain holds its decision lock through
                    # budgeted eviction ticks — slow, not dead.
                    # Dead-marking it would abort its rendezvous
                    # parts and rebuild the very state the drain is
                    # retiring; skip until the drain clears intent.
                    self.health_skips_draining_total += 1
                    continue
                self.health_checks_total += 1
                try:
                    ok = rep.transport.healthz()
                except ReplicaUnavailable:
                    ok = False
                if not ok:
                    failed += 1
                    self.health_failures_total += 1
                    log.error("replica %s failed its health check; "
                              "marking dead (crash_replica semantics)",
                              rep.name)
                    self._mark_replica_dead(rep.index)
        return failed

    def _mark_replica_dead(self, idx: int) -> None:
        """A subprocess replica's daemon is gone/unreachable: its
        in-memory state is unreachable exactly like a killed process's
        — dead, not merely partitioned (a partition is an explicit
        chaos injection; the health checker cannot tell a hung daemon
        from a dead one and must fail to the safe side: rebuild)."""
        rep = self.replicas[idx]
        rep.alive = False
        rep.killed = True
        self._drop_dead_alloc_cache(idx)

    # -- drain intent (ISSUE 19) ----------------------------------------------
    def register_drain_intent(self, idx: int) -> None:
        """A DrainCoordinator on replica ``idx`` is beginning its
        choreography: shield the replica from dead-marking (see
        ``health_check``) until the intent clears."""
        with self._lock:
            self._drain_intent.add(idx)

    def clear_drain_intent(self, idx: int) -> None:
        with self._lock:
            self._drain_intent.discard(idx)

    def pull_evictions(self) -> int:
        """Drain each subprocess replica's local eviction queue onto
        the router's shared bus (in-process replicas write the shared
        deque directly — nothing to pull). The harness's
        drain_evictions and the sweep janitor both run this, so a
        worker-side rollback's victims surface within the round."""
        if self.mode != "subprocess":
            return 0
        pulled = 0
        results = self._fan_out(
            self._alive(), lambda rep: rep.transport.drain_evictions()
        )
        for pods in results.values():
            for pod_key in pods:
                self.pending_evictions.append(pod_key)
                pulled += 1
        return pulled

    def advance_replicas(self, seconds: float) -> None:
        """Fan a FakeClock advance out to every subprocess worker so
        scheduling-semantic time (TTL sweeps, pending expiry) moves in
        lockstep with the router's clock; no-op in-process (shared
        clock object). Simulated time passes EVERYWHERE: a PARTITIONED
        replica still gets the advance (in-process, a partitioned
        replica shares the router's clock — its TTLs keep aging; the
        partition is a routing fiction, not a time freeze), only a
        KILLED process is skipped (gone; its restart re-stamps
        reservations against its fresh clock)."""
        if self.mode != "subprocess":
            return
        self._fan_out(
            [r for r in self.replicas if not r.killed],
            lambda rep: rep.transport.advance(seconds),
        )

    # -- the decision surface -------------------------------------------------
    def handle(self, kind: str, body: Any) -> Any:
        if self._sole is not None:
            return self._sole.handle(kind, body)
        if kind in ("filter", "prioritize"):
            return self._handle_scoring(kind, body)
        if kind == "bind":
            return self._handle_bind(body)
        if kind == "release":
            return self._handle_release(body)
        if kind == "victim_gone":
            cleared = False
            for rep in self._alive():
                try:
                    out = rep.transport.handle(kind, body)
                except ReplicaUnavailable:
                    continue
                cleared = cleared or bool(out.get("cleared"))
            return {"cleared": cleared}
        if kind == "reconcile":
            changed = False
            for rep in self._alive():
                try:
                    if rep.transport.allocation(
                            body["pod_key"]) is None:
                        continue
                    out = rep.transport.handle(kind, body)
                except ReplicaUnavailable:
                    continue
                changed = changed or bool(out.get("changed"))
            return {"changed": changed}
        if kind == "upsert_node":
            idx = self._replica_for_node(
                body["name"], dict(body.get("annotations") or {})
            )
            if idx is None:
                return {"ours": False}
            if not self.replicas[idx].alive:
                return {"error": f"replica {self.replicas[idx].name} "
                                 f"unavailable"}
            try:
                return self.replicas[idx].transport.handle(kind, body)
            except ReplicaUnavailable:
                return {"error": f"replica "
                                 f"{self.replicas[idx].name} died "
                                 f"mid-upsert"}
        raise ValueError(f"unknown decision kind {kind!r}")

    def upsert_nodes_many(
        self, items: list[dict[str, Any]]
    ) -> list[Any]:
        """Batched node ingest: route each {name, annotations} item to
        its owning replica and fan the per-replica batches out
        concurrently — the harness's node sync pays one round-trip per
        replica instead of one per node (at 10k nodes the per-node
        round-trips dominated process-mode setup)."""
        if self._sole is not None:
            return [self._sole.handle("upsert_node", it) for it in items]
        order: dict[int, list[int]] = {}
        results: list[Any] = [None] * len(items)
        for pos, item in enumerate(items):
            idx = self._replica_for_node(
                item["name"], dict(item.get("annotations") or {})
            )
            if idx is None:
                results[pos] = {"ours": False}
                continue
            if not self.replicas[idx].alive:
                results[pos] = {
                    "error": f"replica {self.replicas[idx].name} "
                             f"unavailable"
                }
                continue
            order.setdefault(idx, []).append(pos)
        with self._traced("upsert_nodes", nodes=len(items)):
            out = self._fan_out(
                [self.replicas[i] for i in order],
                lambda rep: rep.transport.upsert_nodes(
                    [items[p] for p in order[rep.index]]
                ),
            )
        for idx, positions in order.items():
            per = out.get(idx)
            for j, pos in enumerate(positions):
                if per is None:  # died mid-batch
                    results[pos] = {
                        "error": f"replica r{idx} died mid-upsert"
                    }
                else:
                    results[pos] = per[j]
        return results

    def _handle_release(self, body: Any) -> Any:
        pod_key = body["pod_key"]
        with self._lock:
            idx = self._pod_replica.pop(pod_key, None)
            self._pod_attempts.pop(pod_key, None)
        targets = (
            [self.replicas[idx]] if idx is not None
            else list(self.replicas)
        )
        with self._traced("release", pod_key):
            for rep in targets:
                if not rep.alive:
                    # a dead replica's release is lost exactly like a
                    # real crashed daemon's: the restart rebuild
                    # (killed) or the post-heal lifecycle resync
                    # (partitioned) re-converges against the pod store
                    continue
                try:
                    rep.transport.handle("release",
                                         {"pod_key": pod_key})
                except ReplicaUnavailable:
                    continue  # died mid-release: same lost-release
                    # contract
        with self._lock:
            self._alloc_cache.pop(pod_key, None)
        return None

    def _handle_scoring(self, kind: str, body: Any) -> Any:
        pod, nodes, names = kube.parse_extender_args(body)
        parts: Optional[dict[int, list]] = None
        if nodes is not None:
            parts = self._partition_nodes(nodes)
            # every owning replica ingests its node objects NOW (the
            # webhook is how topology reaches the caches; only the
            # target replica gets the scoring call, but a later
            # spillover to another replica must find its nodes known).
            # payload_matches makes the unchanged-resend case cheap.
            for idx, pnodes in parts.items():
                rep = self.replicas[idx]
                if not rep.alive:
                    continue
                items = []
                for obj in pnodes:
                    name, annotations = kube.node_name_and_annotations(
                        obj
                    )
                    items.append({"name": name,
                                  "annotations": annotations})
                try:
                    for item, out in zip(items,
                                         rep.transport.upsert_nodes(
                                             items)):
                        if isinstance(out, dict) and out.get("error"):
                            log.error("node %s rejected by %s at "
                                      "ingest: %s", item["name"],
                                      rep.name, out["error"])
                except ReplicaUnavailable:
                    continue  # marked dead; scoring routes around it
        bad_ask = False
        try:
            ask = Extender.device_request(pod)
        except ExtenderError:
            # malformed request (e.g. both TPU and vTPU asked): MUST
            # route to a replica so its handler reports the schema
            # error exactly like the unsharded planner — the non-TPU
            # fast exit below would silently answer it feasible
            # everywhere
            ask = None
            bad_ask = True
        if ask is None and pod.group is None and not bad_ask:
            # non-TPU pod: feasible everywhere, tracked nowhere — no
            # replica needs to see it (matches the unsharded fast exit)
            if names is None and nodes is None:
                # NodesCached body: expand from the federated cache,
                # exactly as the unsharded handler expands from its own
                names = list(self.state.node_names())
            if kind == "prioritize":
                return kube.host_priority_list(
                    {n: 0 for n in (names or [])}
                )
            if nodes is not None:
                return kube.filter_result(list(nodes), {})
            return kube.filter_result_names(list(names or []), {})
        with self._traced(kind, pod.key()):
            if pod.group is not None:
                idx = self._route_gang(pod)
            else:
                with self._lock:
                    idx = self._pod_replica.get(pod.key())
                if idx is None or not self.replicas[idx].alive:
                    idx = self._pick_pod_replica(pod.key())
            return self._score_on(kind, body, pod, parts, idx)

    @staticmethod
    def _sub_body(body: Any, parts: Optional[dict[int, list]],
                  idx: int) -> dict:
        """The body replica ``idx`` sees: its own node objects in raw
        mode; the verbatim body otherwise (a names-only replica
        answers foreign names infeasible on its own — correct, and
        O(1) under plan-served answers)."""
        if parts is None:
            return body
        sub = dict(body)
        sub["Nodes"] = {"Items": parts.get(idx, [])}
        sub.pop("NodeNames", None)
        return sub

    def _score_on(self, kind: str, body: Any, pod: PodInfo,
                  parts: Optional[dict[int, list]], idx: int) -> Any:
        """Forward a filter/prioritize to replica ``idx``. For a
        non-gang filter, spill over to the other alive replicas
        (emptiest first) when the target answers nothing feasible —
        slice affinity routes, the fleet answers. Nodes on other
        shards simply stay out of the feasible set (the upstream
        protocol prunes whatever the answer omits)."""
        def spill_order():
            # built lazily: the common primary-feasible case must not
            # pay O(replicas x slices) utilization reads per webhook
            yield idx
            if kind != "filter" or pod.group is not None:
                return
            for r in sorted(
                self._alive(),
                key=lambda r: (self.state_utilization_of(r), r.index),
            ):
                if r.index != idx:
                    yield r.index

        last_out: Any = None
        for i in spill_order():
            rep = self.replicas[i]
            if not rep.alive or (parts is not None and i not in parts):
                continue
            try:
                out = rep.transport.handle(
                    kind, self._sub_body(body, parts, i)
                )
            except ReplicaUnavailable:
                continue  # died mid-score: spill to the next replica
            if kind == "prioritize":
                return out  # scores for the target's own nodes
            feasible_names = out.get("NodeNames") or []
            last_out = out
            if feasible_names and not out.get("Error"):
                with self._lock:
                    self._pod_replica[pod.key()] = i
                rep.pods_routed += 1
                if i == idx:
                    self._decide(
                        pod.key(), "route", replica=rep.name,
                        feasible=len(feasible_names),
                        **({"gang": f"{pod.namespace}/{pod.group.name}"}
                           if pod.group is not None else {}),
                    )
                else:
                    self._decide(
                        pod.key(), "spillover",
                        primary=self.replicas[idx].name,
                        replica=rep.name,
                        feasible=len(feasible_names),
                    )
                return out
        if last_out is not None:
            return last_out
        if kind == "prioritize":
            return kube.host_priority_list({})
        mk = (kube.filter_result if parts is not None
              else kube.filter_result_names)
        return mk([], {}, error="no alive planner replica owns any "
                                "offered node")

    def _bind_target(self, body: Any) -> tuple[str, Optional[int],
                                               Optional[dict]]:
        """Resolve a bind body to (pod key, owning replica index,
        inline error response). Exactly one of the last two is set."""
        name, ns, uid, node = kube.parse_binding_args(body)
        key = f"{ns}/{name}"
        with self._lock:
            idx = self._node_replica.get(node)
            if idx is None:
                idx = self._pod_replica.get(key)
        if idx is None:
            return key, None, kube.binding_result(
                f"{key}: node {node} is owned by no planner replica"
            )
        rep = self.replicas[idx]
        if not rep.alive:
            return key, None, kube.binding_result(
                f"{key}: replica {rep.name} unavailable (partitioned "
                f"or restarting); scheduler will retry"
            )
        return key, idx, None

    def _after_bind(self, key: str, idx: int, out: Any) -> Any:
        """Post-bind bookkeeping for one replica answer: record the
        pod's affinity, retire its rotation counter, globalize a
        rendezvous member's gang env, and run the eager commit check
        (a replica killed right after the final bind must not read as
        'part lost pre-commit')."""
        if isinstance(out, dict) and not out.get("Error"):
            with self._lock:
                self._pod_replica[key] = idx
                self._pod_attempts.pop(key, None)
                rdv = next(
                    (r for r in self._dcn.values()
                     if key in r.member_target), None,
                )
            if self.mode == "subprocess":
                payload = (out.get("Annotations") or {}).get(
                    codec.ANNO_ALLOC)
                if payload:
                    try:
                        alloc = codec.decode_alloc(payload)
                    except codec.CodecError:
                        alloc = None
                    if alloc is not None:
                        # the federated allocation() fast path: the
                        # lifecycle loop's per-release existence check
                        # answers locally instead of one HTTP read per
                        # released pod (advisory — divergence checks
                        # read the replicas' own ledgers)
                        with self._lock:
                            self._alloc_cache[key] = alloc
            if rdv is not None:
                self._globalize_gang_env(out, rdv)
                # EAGER commit check at the bind that may have closed
                # the last part's quorum: waiting for the next janitor
                # sweep leaves a window where a replica killed after
                # the final bind reads as "part lost pre-commit" and
                # the janitor dissolves a fully-committed gang
                self._check_rendezvous_commit(rdv)
        return out

    def _handle_bind(self, body: Any) -> Any:
        key, idx, err = self._bind_target(body)
        if err is not None:
            return err
        with self._traced("bind", key):
            try:
                out = self.replicas[idx].transport.handle("bind", body)
            except ReplicaUnavailable:
                return kube.binding_result(
                    f"{key}: replica {self.replicas[idx].name} died "
                    f"mid-bind; scheduler will retry"
                )
        return self._after_bind(key, idx, out)

    def bind_many(self, bodies: list[dict]) -> list[dict]:
        """Batched binds for the driver path: group by owning replica,
        fan the per-replica batches out concurrently (each replica's
        connection keeps ITS binds ordered), then run the same
        post-bind bookkeeping per answer. Answer order matches input
        order. The per-pod webhook path (``handle('bind', ...)``)
        stays untouched — this is how the process mode keeps the
        commit step off the per-pod round-trip ledger."""
        if self._sole is not None:
            return [self._sole.handle("bind", b) for b in bodies]
        results: list[Optional[dict]] = [None] * len(bodies)
        order: dict[int, list[int]] = {}
        keys: list[Optional[str]] = [None] * len(bodies)
        for pos, body in enumerate(bodies):
            key, idx, err = self._bind_target(body)
            keys[pos] = key
            if err is not None:
                results[pos] = err
                continue
            order.setdefault(idx, []).append(pos)
        with self._traced("bind_many", pods=len(bodies)):
            out = self._fan_out(
                [self.replicas[i] for i in order],
                lambda rep: rep.transport.bind_many(
                    [bodies[p] for p in order[rep.index]]
                ),
            )
        for idx, positions in order.items():
            per = out.get(idx)
            for j, pos in enumerate(positions):
                if per is None:
                    results[pos] = kube.binding_result(
                        f"{keys[pos]}: replica r{idx} died mid-bind; "
                        f"scheduler will retry"
                    )
                else:
                    results[pos] = self._after_bind(
                        keys[pos], idx, per[j]
                    )
        return results

    def _check_rendezvous_commit(self, rdv: _Rendezvous) -> None:
        """Flip the rendezvous to committed the moment every part's
        local reservation is committed (idempotent; also run by the
        janitor sweep for the webhook-paced path)."""
        if rdv.committed:
            return
        for idx in rdv.parts:
            rep = self.replicas[idx]
            if not rep.alive:
                return
            res = self._reservation_of(rep, rdv.key)
            if res is None or not res["committed"]:
                return
        rdv.committed = True
        with self._lock:
            self.rendezvous_committed += 1
        self.events.emit(
            "GangCommitted", obj=f"gang/{rdv.key[0]}/{rdv.key[1]}",
            message=(f"rendezvous committed: all {len(rdv.parts)} "
                     f"parts assembled"),
        )
        self._decide_rendezvous(
            "", rdv.key, outcome="committed",
            parts=self._rdv_parts_doc(self.replicas, rdv.parts))

    def _globalize_gang_env(self, out: dict, rdv: _Rendezvous) -> None:
        """A rendezvous member's bind answer carries the TPU_KUBE_GANG_*
        env of its LOCAL part (the replica only knows its own slices);
        rewrite the annotation to the GLOBAL rendezvous topology so the
        in-pod runtime forms the full multislice collective — the same
        contract a single-planner DCN gang's bind stamps."""
        from tpukube.device.tpu import (
            ENV_GANG_NUM_SLICES,
            ENV_GANG_SLICE_INDEX,
            ENV_GANG_SLICES,
        )

        payload = (out.get("Annotations") or {}).get(codec.ANNO_ALLOC)
        if not payload:
            return
        try:
            alloc = codec.decode_alloc(payload)
        except codec.CodecError:
            return
        # the pod's OWN slice comes from its local index into the
        # part's local slice list — a part may span several slices,
        # so the first local slice is NOT every member's slice
        local_sids = [s for s in
                      alloc.env.get(ENV_GANG_SLICES, "").split(",") if s]
        try:
            local_idx = int(alloc.env.get(ENV_GANG_SLICE_INDEX, ""))
            local_sid = local_sids[local_idx]
        except (ValueError, IndexError):
            return
        sids = sorted({
            sid for parts in rdv.parts.values() for sid in parts
        })
        if local_sid not in sids:
            return
        env = dict(alloc.env)
        env[ENV_GANG_NUM_SLICES] = str(len(sids))
        env[ENV_GANG_SLICES] = ",".join(sids)
        env[ENV_GANG_SLICE_INDEX] = str(sids.index(local_sid))
        out["Annotations"][codec.ANNO_ALLOC] = codec.encode_alloc(
            dc_replace(alloc, env=env)
        )

    # -- batch-driver surface -------------------------------------------------
    def _route_pod(self, pod: PodInfo) -> int:
        """The target replica for one driver-admitted pod."""
        key = pod.key()
        if pod.group is not None:
            return self._route_gang(pod)
        # one lock round-trip for the whole routing read (this is
        # the per-pod driver hot path)
        with self._lock:
            idx = self._pod_replica.get(key)
            attempts = self._pod_attempts.get(key, 0)
        if idx is None or not self.replicas[idx].alive:
            idx = self._pick_pod_replica(key, attempts)
        return idx

    def admit(self, pod: PodInfo) -> bool:
        if self._sole is not None:
            return self._sole.admit(pod)
        return self.admit_many([pod])[0]

    def admit_many(self, pods: list[PodInfo]) -> list[bool]:
        """Batched admissions: route every pod, then fan ONE admit call
        per target replica out concurrently. Result order matches the
        input. This is the driver hot path the process mode lives on —
        per-pod round-trips would hand the router tax the whole
        multi-core win back."""
        if self._sole is not None:
            return [self._sole.admit(p) for p in pods]
        results: list[bool] = [False] * len(pods)
        order: dict[int, list[int]] = {}
        with self._traced("admit_many", pods=len(pods)):
            for pos, pod in enumerate(pods):
                idx = self._route_pod(pod)
                if not self.replicas[idx].alive:
                    continue
                order.setdefault(idx, []).append(pos)
            out = self._fan_out(
                [self.replicas[i] for i in order],
                lambda rep: rep.transport.admit_many(
                    [pods[p] for p in order[rep.index]]
                ),
            )
        for idx, positions in order.items():
            per = out.get(idx)
            if per is None:
                continue  # replica died mid-admit: pods re-admit later
            rep = self.replicas[idx]
            for j, pos in enumerate(positions):
                ok = bool(per[j])
                results[pos] = ok
                if ok:
                    with self._lock:
                        self._pod_replica[pods[pos].key()] = idx
                    rep.pods_routed += 1
                    self._decide(pods[pos].key(), "route",
                                 replica=rep.name)
        return results

    def plan_pending(self) -> int:
        """Drive every replica's batch planner. In process mode the N
        plan calls fan out CONCURRENTLY — one planner process per core
        actually planning in parallel, the throughput lever the
        in-process sweep could never pull (one GIL)."""
        if self._sole is not None:
            return self._sole.plan_pending()
        self.sweep()
        with self._traced("plan_pending"):
            out = self._fan_out(
                self._alive(), lambda rep: rep.transport.plan_pending()
            )
        return sum(out.values())

    def _planned_miss(self, pod_key: str, idx: int) -> None:
        """Plan failed or expired on the owner: release the affinity
        and bump the attempt count so the next admit rotates to
        another replica instead of re-queuing on the same full shard
        forever."""
        with self._lock:
            if self._pod_replica.get(pod_key) == idx:
                self._pod_replica.pop(pod_key, None)
            self._pod_attempts[pod_key] = \
                self._pod_attempts.get(pod_key, 0) + 1

    def planned_node(self, pod_key: str) -> Optional[str]:
        if self._sole is not None:
            return self._sole.planned_node(pod_key)
        return self.planned_many([pod_key])[pod_key]

    def planned_many(
        self, pod_keys: list[str]
    ) -> dict[str, Optional[str]]:
        """Batched plan queries: keys with a recorded replica affinity
        resolve in one call per replica (fanned out concurrently);
        unmapped keys scan the live set. Misses run the same
        rotation bookkeeping as ``planned_node``."""
        if self._sole is not None:
            return {k: self._sole.planned_node(k) for k in pod_keys}
        results: dict[str, Optional[str]] = {}
        order: dict[int, list[str]] = {}
        unmapped: list[str] = []
        with self._lock:
            affinity = {k: self._pod_replica.get(k) for k in pod_keys}
        for key in pod_keys:
            idx = affinity[key]
            if idx is not None and self.replicas[idx].alive:
                order.setdefault(idx, []).append(key)
            else:
                unmapped.append(key)
        out = self._fan_out(
            [self.replicas[i] for i in order],
            lambda rep: rep.transport.planned_nodes(order[rep.index]),
        )
        for idx, keys in order.items():
            per = out.get(idx)
            for key in keys:
                node = per.get(key) if per is not None else None
                results[key] = node
                if node is None:
                    self._planned_miss(key, idx)
        if unmapped:
            for key in unmapped:
                results[key] = None
            scan = self._fan_out(
                self._alive(),
                lambda rep: rep.transport.planned_nodes(unmapped),
            )
            for nodes in scan.values():
                for key, node in nodes.items():
                    if node is not None and results.get(key) is None:
                        results[key] = node
        return results

    def release(self, pod_key: str) -> None:
        self.handle("release", {"pod_key": pod_key})

    def release_many(self, pod_keys: list[str]) -> None:
        """Batched releases (the lifecycle loop's resync flush): keys
        group by recorded pod->replica affinity and fan out as ONE
        call per replica; keys with no affinity go to every alive
        replica (a release of an unknown pod is a no-op there). Same
        lost-release contract as ``_handle_release`` for dead
        replicas."""
        if self._sole is not None:
            for key in pod_keys:
                self._sole.handle("release", {"pod_key": key})
            return
        order: dict[int, list[str]] = {}
        everywhere: list[str] = []
        with self._lock:
            for key in pod_keys:
                idx = self._pod_replica.pop(key, None)
                self._pod_attempts.pop(key, None)
                self._alloc_cache.pop(key, None)
                if idx is None:
                    everywhere.append(key)
                else:
                    order.setdefault(idx, []).append(key)
        if everywhere:
            for rep in self._alive():
                order.setdefault(rep.index, []).extend(everywhere)
        self._fan_out(
            [self.replicas[i] for i in order
             if self.replicas[i].alive],
            lambda rep: rep.transport.release_many(order[rep.index]),
        )

    # -- restart / recovery ---------------------------------------------------
    def rebuild_from_pods(self, pods: list[dict[str, str]]) -> int:
        """Cold rebuild across the partition: pods route to the
        replica owning their bound node; the pod-group annotations of
        a COMMITTED DCN-rendezvous gang (members spanning >1 replica,
        quorum present) are rewritten to each part's LOCAL member
        count so every part restores committed-verbatim — the
        rendezvous record itself is then re-registered. A PARTIAL
        DCN gang restores with its original annotations, so each part
        rolls its members back: all-or-nothing in death, exactly the
        single-planner restore contract."""
        if self._sole is not None:
            return self._sole.rebuild_from_pods(pods)
        by_replica: dict[int, list[dict[str, str]]] = {}
        gangs: dict[tuple[str, str], list[tuple[int, dict, Any]]] = {}
        skipped = 0
        for annotations in pods:
            payload = annotations.get(codec.ANNO_ALLOC)
            if not payload:
                continue
            try:
                alloc = codec.decode_alloc(payload)
            except codec.CodecError:
                skipped += 1
                continue
            idx = self._replica_for_node(alloc.node_name)
            if idx is None:
                log.error("rebuild: %s bound to unmapped node %s; "
                          "skipped", alloc.pod_key, alloc.node_name)
                skipped += 1
                continue
            by_replica.setdefault(idx, []).append(annotations)
            try:
                group = codec.pod_group_from_annotations(annotations)
            except codec.CodecError:
                group = None
            if group is not None:
                ns = alloc.pod_key.split("/", 1)[0]
                gangs.setdefault((ns, group.name), []).append(
                    (idx, annotations, group)
                )
        rewrites: dict[tuple[str, str], dict[int, int]] = {}
        for key, members in gangs.items():
            replicas_of = {idx for idx, _, _ in members}
            group = members[0][2]
            if len(replicas_of) > 1 and len(members) >= group.min_member:
                # committed DCN gang: each part restores by its LOCAL
                # quorum (the full min_member would read as partial
                # everywhere and roll a healthy gang back)
                counts: dict[int, int] = {}
                for idx, _, _ in members:
                    counts[idx] = counts.get(idx, 0) + 1
                rewrites[key] = counts
                for idx, annotations, g in members:
                    annotations.update(codec.pod_group_annotations(
                        PodGroup(name=g.name,
                                 min_member=counts[idx],
                                 shape=None, allow_dcn=True)
                    ))
        restored = 0
        for idx, plist in sorted(by_replica.items()):
            try:
                restored += self.replicas[idx].transport \
                    .rebuild_from_pods(plist)
            except ReplicaUnavailable:
                log.error("rebuild: replica r%d unreachable; its %d "
                          "pod(s) restore at its own restart", idx,
                          len(plist))
                continue
            with self._lock:
                for annotations in plist:
                    payload = annotations.get(codec.ANNO_ALLOC)
                    if payload:
                        try:
                            alloc = codec.decode_alloc(payload)
                        except codec.CodecError:
                            continue
                        self._pod_replica[alloc.pod_key] = idx
                        if self.mode == "subprocess":
                            self._alloc_cache[alloc.pod_key] = alloc
        for key, counts in rewrites.items():
            parts: dict[int, dict[str, list[TopologyCoord]]] = {}
            for idx in counts:
                res = self._reservation_of(self.replicas[idx], key)
                if res is not None:
                    parts[idx] = res["slices"]
            if len(parts) > 1:
                rdv = _Rendezvous(
                    key, parts,
                    {idx: counts[idx] for idx in parts},
                    created=self.clock.monotonic(),
                )
                rdv.committed = True
                with self._lock:
                    self._dcn[key] = rdv
        return restored

    def replica_pods(self, idx: int,
                     pods: dict[str, dict[str, Any]]) -> list[dict]:
        """The pod store entries bound to replica ``idx``'s nodes (the
        harness's per-replica restart feed)."""
        out = []
        with self._lock:
            owned = {n for n, i in self._node_replica.items()
                     if i == idx}
        for pod in pods.values():
            node = (pod.get("spec") or {}).get("nodeName")
            if node in owned:
                out.append(pod)
        return out

    def kill_replica(self, idx: int) -> None:
        """Model replica process death: everything in-memory on the
        shard — ledger, reservations, queue, plans — is gone; nothing
        is flushed. The router keeps routing around it, the federated
        read views stop serving the corpse's ledger (``killed``), and
        the rendezvous janitor aborts any uncommitted rendezvous
        holding a part there."""
        rep = self.replicas[idx]
        rep.alive = False
        rep.killed = True
        self._drop_dead_alloc_cache(idx)
        ext = rep.extender
        if ext is not None:
            if ext.journal is not None:
                ext.journal.crash()
            ext.state.retire()
        else:
            # subprocess replica: REAL process death (SIGKILL) —
            # nothing modeled, nothing flushed
            rep.transport.kill()

    def partition_replica(self, idx: int) -> None:
        """Model a network partition: the replica's state survives but
        the router cannot reach it — scoring/bind answers route
        around or fail retryably, and an uncommitted rendezvous part
        there counts as lost (all-or-nothing abort)."""
        self.replicas[idx].alive = False

    def heal_replica(self, idx: int) -> None:
        """End a partition: the replica serves again with the state it
        kept — MINUS any fragment of a rendezvous the janitor aborted
        while THIS replica was unreachable (a locally-complete part of
        a dead gang must die all-or-nothing, not resurrect as a
        fragment). The sentence is scoped to the exact replicas that
        were unreachable at abort time, so a same-named gang
        re-created meanwhile on other replicas is never touched.
        Other reservations resolve through the normal janitors."""
        rep = self.replicas[idx]
        rep.alive = True
        self._settle_aborted_parts(idx)

    def _settle_aborted_parts(self, idx: int) -> None:
        """Dissolve replica ``idx``'s leftover fragments of rendezvous
        aborted while it was unreachable, and retire it from every
        pending sentence (heal AND restart both come through here —
        either way the replica's state is now reconciled)."""
        rep = self.replicas[idx]
        with self._lock:
            owed = [key for key, pending in self._aborted_dcn.items()
                    if idx in pending]
        settled = []
        for key in owed:
            if self._reservation_of(rep, key) is not None:
                log.warning(
                    "replica %s returned holding part of aborted "
                    "rendezvous %s/%s; dissolving", rep.name, *key,
                )
                try:
                    rep.transport.gang_dissolve(key)
                except ReplicaUnavailable:
                    continue  # died again: stays on the pending sentence
            settled.append(key)
        with self._lock:
            for key in settled:
                pending = self._aborted_dcn.get(key)
                if pending is not None:
                    pending.discard(idx)
                    if not pending:
                        self._aborted_dcn.pop(key, None)

    def _rewrite_rdv_quorum(
        self, annotations: dict[str, str], ns: Optional[str],
        live_rdv: dict, idx: int,
    ) -> dict[str, str]:
        """A live-rendezvous member's pod-group annotations rewritten
        to the part's LOCAL quorum (the full min_member would read as
        partial on one replica and roll a healthy gang back); anything
        else passes through verbatim. Returns a fresh dict."""
        annotations = dict(annotations)
        try:
            group = codec.pod_group_from_annotations(annotations)
        except codec.CodecError:
            group = None
        if group is not None:
            # the rendezvous key is (namespace, group): an unrelated
            # same-named gang in ANOTHER namespace must not have its
            # quorum rewritten
            if ns is None:
                payload = annotations.get(codec.ANNO_ALLOC)
                if payload:
                    try:
                        ns = codec.decode_alloc(payload).pod_key.split(
                            "/", 1)[0]
                    except codec.CodecError:
                        ns = None
            rdv = (live_rdv.get((ns, group.name))
                   if ns is not None else None)
            if rdv is not None:
                annotations.update(codec.pod_group_annotations(
                    PodGroup(name=group.name,
                             min_member=rdv.local_min[idx],
                             shape=None, allow_dcn=True)
                ))
        return annotations

    def restart_replica(
        self, idx: int,
        node_annotations: list[tuple[str, dict[str, str]]],
        pods: list[dict[str, str]],
        pod_objects: Optional[list[dict]] = None,
    ) -> int:
        """Restart one killed replica the way a restarted shard daemon
        would: a fresh Extender (in-process) or a freshly spawned
        worker daemon (subprocess). With the replica's journal segment
        enabled (and ``pod_objects`` — the full pod objects of the
        shard — provided), the restart REPLAYS the segment first
        (checkpoint + WAL through the real recovery, reconciled
        against the provided node/pod truth) so a warm worker restart
        rides its own durable log instead of a full re-ingest (ROADMAP
        sharding item (d)); the failure ladder falls back to the cold
        path — nodes re-ingested, ledger + gang reservations rebuilt
        from pod annotations (``rebuild_from_pods``) — on a FRESH
        replica. Live-rendezvous parts restore by their LOCAL quorum
        either way. Returns allocations restored."""
        old = self.replicas[idx]
        fake_clock = hasattr(self.clock, "advance")
        # stat the durable segment BEFORE the fresh replica's journal
        # re-creates the (empty) WAL file: no pre-crash bytes on disk
        # means the warm path has nothing to replay — go cold
        seg = self._replica_cfgs[idx].journal_path
        has_segment = bool(seg) and (
            os.path.exists(seg) or os.path.exists(seg + ".ckpt"))

        def make_transport():
            if self.mode == "subprocess":
                return self._make_transport(
                    idx, self._replica_cfgs[idx], fake_clock
                )
            ext = Extender(
                self._replica_cfgs[idx], clock=self.clock,
                eviction_sink=self.pending_evictions,
            )
            # every externally-wired hook survives the restart (a fresh
            # daemon would be re-wired by its main; the router plays
            # that role here) — dropping the degraded gate would let
            # ONE restarted shard bind while the rest of the plane
            # refuses
            ext.evict_precheck = old.extender.evict_precheck
            ext.binder = old.extender.binder
            ext.degraded_gate = old.extender.degraded_gate
            return InProcessTransport(ext)

        if self.mode == "subprocess":
            try:
                old.transport.kill()  # reap a half-dead daemon first
            except (OSError, subprocess.SubprocessError) as e:
                log.warning("restart r%d: old worker reap failed: %s",
                            idx, e)
        self.replicas[idx] = PlannerReplica(idx, make_transport())
        rep = self.replicas[idx]
        with self._lock:
            live_rdv = {
                key: rdv for key, rdv in self._dcn.items()
                if idx in rdv.parts
            }
        restored: Optional[int] = None
        warm = False
        if (pod_objects is not None
                and self._replica_cfgs[idx].journal_enabled
                and has_segment):
            # warm path: the replica's own journal segment. The feed's
            # rendezvous members carry their LOCAL quorum (the same
            # rewrite the cold plist gets) so the recovery reconcile
            # can never misread a healthy part as partial.
            node_objs = [
                {"metadata": {"name": name,
                              "annotations": dict(annotations)}}
                for name, annotations in node_annotations
            ]
            fixed_pods = []
            for obj in pod_objects:
                meta = dict(obj.get("metadata") or {})
                meta["annotations"] = self._rewrite_rdv_quorum(
                    dict(meta.get("annotations") or {}),
                    meta.get("namespace", "default"), live_rdv, idx,
                )
                fixed_pods.append({**obj, "metadata": meta})
            try:
                out = rep.transport.recover(node_objs, fixed_pods)
            except ReplicaUnavailable:
                out = {"recover_error": "replica unreachable during "
                                        "recovery"}
            err = out.get("recover_error")
            if err is None:
                restored = int(out.get("restored", 0))
                warm = True
                log.warning(
                    "restart r%d: journal segment replayed (%d "
                    "alloc(s) restored warm)", idx, restored)
            else:
                # failure ladder: cold full re-ingest on a FRESH
                # replica (the failed recovery may have half-restored
                # state; a fresh daemon/Extender starts clean)
                log.error("restart r%d: journal recovery failed (%s); "
                          "falling back to the full re-ingest", idx,
                          err)
                if self.mode == "subprocess":
                    try:
                        rep.transport.kill()
                    except (OSError, subprocess.SubprocessError):
                        pass
                self.replicas[idx] = PlannerReplica(idx,
                                                    make_transport())
                rep = self.replicas[idx]
        if restored is None:
            items = [{"name": name, "annotations": annotations}
                     for name, annotations in node_annotations]
            for item, out in zip(items,
                                 rep.transport.upsert_nodes(items)):
                if isinstance(out, dict) and out.get("error"):
                    log.error("restart r%d: node %s rejected: %s",
                              idx, item["name"], out["error"])
            plist = [
                self._rewrite_rdv_quorum(annotations, None, live_rdv,
                                         idx)
                for annotations in pods
            ]
            restored = rep.transport.rebuild_from_pods(plist)
            recovered_allocs = []
            for annotations in plist:
                payload = annotations.get(codec.ANNO_ALLOC)
                if payload:
                    try:
                        recovered_allocs.append(
                            codec.decode_alloc(payload))
                    except codec.CodecError:
                        continue
        else:
            # warm path: prime the router maps from what ACTUALLY
            # restored (recovery may have reconciled stale pods away)
            try:
                recovered_allocs = rep.transport.allocations()
            except ReplicaUnavailable:
                recovered_allocs = []
        with self._lock:
            for alloc in recovered_allocs:
                self._pod_replica[alloc.pod_key] = idx
                if self.mode == "subprocess":
                    self._alloc_cache[alloc.pod_key] = alloc
            # which path this restart actually took (tests + operator
            # introspection: a warm=False restart on a journal-enabled
            # replica means the failure ladder fired)
            self.last_restart = {"replica": idx, "warm": warm,
                                 "restored": restored}
        rep.alive = True
        # a restored fragment of a rendezvous aborted while this
        # replica was down dies here (and the replica leaves the
        # pending sentence); then reconcile the rendezvous records
        # against what actually restored (an uncommitted part that
        # could not re-complete rolled back inside restore(); the
        # janitor then aborts the survivors — all-or-nothing)
        self._settle_aborted_parts(idx)
        self.sweep()
        return restored

    def lockgraph_report(self) -> Optional[dict]:
        """The fleet-wide dynamic lock-order report: this process's
        monitor merged with every subprocess replica's edge set (which
        rides ``replica_summary``'s ``lock_graph`` key over the worker
        status surface — no extra wire protocol). None when no monitor
        is installed here (``lock_monitor`` off).

        In-process replicas share THIS process's ref-counted monitor,
        so their summaries report the same graph the router already
        holds — merging them would only double the counts; they are
        counted as reporting and skipped. Cycle detection runs on the
        merged edge multiset: a worker-process inversion (held->acquired
        the other way around on the far side of the HTTP boundary)
        closes a cycle here exactly as a local one would."""
        from tpukube.analysis import lockgraph

        mon = lockgraph.active()
        if mon is None:
            return None
        own = mon.report()
        sites = dict(own["sites"])
        acquisitions = own["acquisitions"]
        merged: dict[tuple[str, str], int] = {
            (e["from"], e["to"]): e["count"] for e in own["edges"]
        }
        reporting = []
        for rep in self.replicas:
            doc = None
            if not rep.killed:
                try:
                    doc = rep.transport.summary()
                except ReplicaUnavailable:
                    doc = None
            lg = (doc or {}).get("lock_graph")
            if lg is None:
                continue
            reporting.append(rep.name)
            if rep.transport.mode == "inprocess":
                continue  # same process, same monitor: already merged
            acquisitions += lg["acquisitions"]
            for site, n in lg["sites"].items():
                sites[site] = sites.get(site, 0) + n
            for e in lg["edges"]:
                key = (e["from"], e["to"])
                merged[key] = merged.get(key, 0) + e["count"]
        return {
            "sites": dict(sorted(sites.items())),
            "acquisitions": acquisitions,
            "edges": [
                {"from": a, "to": b, "count": n}
                for (a, b), n in sorted(merged.items())
            ],
            "cycles": lockgraph.LockOrderMonitor._cycles_of(merged),
            "replicas_reporting": reporting,
        }

    def shutdown(self) -> None:
        """Close every replica (sinks in-process, graceful daemon stop
        in subprocess mode) — the harness stop path."""
        for rep in self.replicas:
            rep.transport.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if self.trace is not None:
            self.trace.close()
        if self.decisions is not None:
            self.decisions.close()

"""Slice-partitioned control plane (ISSUE 13 tentpole).

BENCH_r06 showed the single planner process as the throughput ceiling:
one ``ClusterState``/``GangManager`` owns the whole fleet, so scenario
12 tops out around 1,650 pods/s at 10,240 nodes — the same
single-extender-webhook shape PAPER.md §1 identifies as KubeGPU's
scaling limit. ICI slices are already the natural partition unit
(snapshots, ``SnapshotDelta`` chains, fragmentation gauges, locks, and
the tenancy ledger are all per-slice), so this module partitions the
control plane BY SLICE:

  * :class:`PlannerReplica` — one shard: a full
    :class:`~tpukube.sched.extender.Extender` owning a DISJOINT slice
    set, with its own ledger, gang manager, snapshot/delta chain,
    scheduling queue, and journal segment (``<journal_path>.r<i>``).
  * :class:`ShardRouter` — the thin routing layer in front of the N
    replicas. It speaks the same decision surface as a single Extender
    (``handle``/``admit``/``plan_pending``/``planned_node``/...), so
    the sim harness, the apiserver loops, and the chaos checkers run
    against either unchanged. Nodes route by the slice id in their
    topology annotation; pods route by slice affinity (their gang's
    home replica, their allocation's owner, or a stable hash with
    capacity spillover); binds route by the target node's owner.

Parity gate: with ``planner_replicas == 1`` every router entry point
delegates VERBATIM to the sole replica's Extender — the N=1 sharded
path is byte-identical to the unsharded planner by construction
(tests/test_shard.py proves it end to end).

Two-phase rendezvous for DCN-spanning gangs
-------------------------------------------

A gang confined to one replica's slices reserves and commits locally,
exactly as today. A gang that fits NO single replica — and opted in to
DCN spanning (``PodGroup.allow_dcn``) — goes through a rendezvous
coordinated by the router on behalf of the initiating (home) replica,
built on the existing ``gang.py`` reservation/epoch machinery:

  1. PLAN: the router asks every alive replica's epoch-cached snapshot
     for its largest contiguous free boxes (one box per slice, each a
     multiple of chips_per_pod — the same greedy
     ``_plan_dcn_split`` shape, spread across replicas).
  2. PREPARE: each participant replica reserves its part through
     ``GangManager.reserve_exact_split`` under its own locks, with a
     LOCAL group whose ``min_member`` is the part's member count — so
     the part commits by its own quorum and sweeps by its own TTL.
     A duplicate prepare is idempotent (``reserve_exact_split``
     returns the existing reservation for the key), and a prepare that
     loses a race (box re-occupied) raises without touching anything.
  3. COMMIT-OR-ABORT: all prepares landed → the rendezvous is
     recorded and member pods fan out to participants with unassigned
     room; any prepare failed → every prepared part is dropped
     (``drop_reservation`` — no members yet, nothing to evict). After
     that, the rendezvous janitor (:meth:`ShardRouter.sweep`) keeps
     the all-or-nothing contract: if ANY uncommitted part disappears —
     TTL expiry, chip/link fault rollback, a replica killed or
     partitioned mid-commit — the surviving parts are dissolved
     (members evicted through the shared eviction bus), exactly the
     death a single-planner gang rollback dies.

The PR 6 reservation-leak prover and the snapshot-audit sentinel keep
holding: every reservation mutation goes through the proven
``gang.py`` seams, and each replica audits its own snapshot chain.

Production shape: this in-process router serves the sim/bench plane;
a real deployment runs one extender process per replica (each
configured with its slice set and journal segment) behind the same
routing contract, with the router as the stateless webhook front —
its maps are re-derivable from node annotations and the replicas'
reservations (see ``rebuild_from_pods``).
"""

from __future__ import annotations

import json
import logging
import threading
import zlib
from collections import deque
from dataclasses import replace as dc_replace
from typing import Any, Optional

from tpukube.core import codec
from tpukube.core.config import TpuKubeConfig
from tpukube.core.types import PodGroup, PodInfo, TopologyCoord
from tpukube.sched import kube, slicefit
from tpukube.sched.extender import Extender, ExtenderError
from tpukube.sched.gang import GangError
from tpukube.sched.state import StateError

log = logging.getLogger("tpukube.shard")


class ShardError(RuntimeError):
    pass


class PlannerReplica:
    """One shard of the control plane: index + its Extender + liveness.
    ``alive=False`` models a partitioned OR killed replica — the
    router stops routing to it and the rendezvous janitor treats its
    uncommitted parts as lost. ``killed=True`` additionally marks the
    in-memory state as GONE (process death): the federated read views
    must not serve the corpse's ledger — a dead shard's pods are
    ledger-absent until the warm restart, and the chaos invariants
    must see exactly that."""

    __slots__ = ("index", "extender", "alive", "killed", "pods_routed")

    def __init__(self, index: int, extender: Extender):
        self.index = index
        self.extender = extender
        self.alive = True
        self.killed = False
        self.pods_routed = 0

    @property
    def name(self) -> str:
        return f"r{self.index}"


class _Rendezvous:
    """Router-side record of one DCN gang's prepared parts."""

    __slots__ = ("key", "parts", "local_min", "created", "committed",
                 "member_target")

    def __init__(self, key: tuple[str, str],
                 parts: dict[int, dict[str, list[TopologyCoord]]],
                 local_min: dict[int, int], created: float):
        self.key = key
        #: replica index -> {slice id -> reserved coords}
        self.parts = parts
        #: replica index -> that part's member quorum
        self.local_min = local_min
        self.created = created
        self.committed = False
        #: pod key -> its part's replica index: STICKY member routing,
        #: capped per part at local_min — the driver path admits every
        #: member before any binds, so ``assignable`` cannot spread
        #: them; the router must (and a member's filter, prioritize,
        #: and bind must all land on the same part)
        self.member_target: dict[str, int] = {}


class _FederatedState:
    """Read-only ledger view over every replica (the surface the
    apiserver loops and chaos checkers consume: ``allocations``,
    ``allocation``, ``utilization``, ``node_names``). Mutations never
    come through here — they route via ``ShardRouter.handle``. A
    KILLED replica's state is excluded: its in-memory ledger died
    with the process, and serving the corpse would let the chaos
    invariants false-negative on exactly the divergence a dead shard
    creates (a partitioned replica's state, by contrast, is real and
    still served)."""

    def __init__(self, router: "ShardRouter"):
        self._router = router

    def _live(self) -> list[PlannerReplica]:
        return [r for r in self._router.replicas if not r.killed]

    def allocations(self) -> list:
        return [
            a
            for rep in self._live()
            for a in rep.extender.state.allocations()
        ]

    def allocation(self, pod_key: str):
        for rep in self._live():
            a = rep.extender.state.allocation(pod_key)
            if a is not None:
                return a
        return None

    def priority_of(self, pod_key: str) -> int:
        a = self.allocation(pod_key)
        return a.priority if a is not None else 0

    def node(self, name: str):
        idx = self._router._node_replica.get(name)
        reps = (
            [self._router.replicas[idx]] if idx is not None
            else self._router.replicas
        )
        for rep in reps:
            if rep.killed:
                continue
            view = rep.extender.state.node(name)
            if view is not None:
                return view
        return None

    def node_names(self) -> tuple[str, ...]:
        out: list[str] = []
        for rep in self._live():
            out.extend(rep.extender.state.node_names())
        return tuple(sorted(out))

    def slice_ids(self) -> list[str]:
        out: list[str] = []
        for rep in self._live():
            out.extend(rep.extender.state.slice_ids())
        return sorted(out)

    def utilization(self) -> float:
        used = total = 0
        for rep in self._live():
            st = rep.extender.state
            for sid in st.slice_ids():
                u, t = st.slice_share_counts(sid)
                used += u
                total += t
        return used / total if total else 0.0

    def retire(self) -> None:
        for rep in self._router.replicas:
            rep.extender.state.retire()


class _RouterCycle:
    """Aggregated batch-planner stats in the shape scenario drivers
    read (``extender.cycle.stats()``)."""

    def __init__(self, router: "ShardRouter"):
        self._router = router

    def _cycles(self) -> list:
        return [
            rep.extender.cycle
            for rep in self._router.replicas
            if rep.extender.cycle is not None
        ]

    @property
    def cycles(self) -> int:
        return sum(c.cycles for c in self._cycles())

    def stats(self) -> dict[str, Any]:
        per = [c.stats() for c in self._cycles()]
        if not per:
            return {"enabled": False}
        summed = {
            k: sum(p[k] for p in per)
            for k in (
                "cycles", "pods_planned", "queue_depth", "plans_live",
                "assumes", "assume_undos", "fast_patches",
                "fast_rebuilds", "gang_batches", "gang_batch_members",
                "plan_hits", "plan_misses",
            )
        }
        lookups = summed["plan_hits"] + summed["plan_misses"]
        wall_total = sum(
            c.cycle_wall_total for c in self._cycles()
        )
        summed.update({
            "enabled": True,
            "replicas": len(per),
            "plan_hit_ratio": (round(summed["plan_hits"] / lookups, 4)
                               if lookups else None),
            "plan_ms_per_pod": (
                round(1000 * wall_total / summed["pods_planned"], 4)
                if summed["pods_planned"] else None
            ),
            "per_replica": {
                self._router.replicas[i].name: {
                    "pods_planned": p["pods_planned"],
                    "cycles": p["cycles"],
                    "plan_ms_per_pod": p["plan_ms_per_pod"],
                }
                for i, p in enumerate(per)
            },
        })
        return summed


class _MergedEvents:
    """Event-journal rollup over the replicas (scenario result code
    reads ``counts_by_reason``; the harness calls ``close``)."""

    def __init__(self, router: "ShardRouter"):
        self._router = router

    def counts_by_reason(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for rep in self._router.replicas:
            for reason, n in rep.extender.events.counts_by_reason().items():
                out[reason] = out.get(reason, 0) + n
        return out

    def emit(self, *args, **kwargs) -> None:
        # router-level events land on replica 0's journal (the
        # rendezvous coordinator's channel)
        self._router.replicas[0].extender.events.emit(*args, **kwargs)

    def close(self) -> None:
        for rep in self._router.replicas:
            rep.extender.events.close()


class ShardRouter:
    """N planner replicas behind one decision surface (see module
    docstring). With ``planner_replicas == 1`` every entry point
    delegates verbatim to the sole Extender — the parity gate."""

    def __init__(self, config: TpuKubeConfig, clock=None):
        n = config.planner_replicas
        if n < 1:
            raise ShardError("planner_replicas must be >= 1")
        self.config = config
        from tpukube.core.clock import SYSTEM

        self.clock = clock if clock is not None else SYSTEM
        #: ONE eviction bus across replicas, so the harness's / the
        #: daemon's single EvictionExecutor drains every shard's
        #: rollback and preemption victims
        self.pending_evictions: deque[str] = deque()
        self.replicas: list[PlannerReplica] = []
        self._replica_cfgs: list[TpuKubeConfig] = []
        for i in range(n):
            rcfg = config
            if n > 1 and config.journal_enabled:
                # per-replica journal segment: each shard's WAL +
                # checkpoints cover exactly its own slice partition
                rcfg = dc_replace(
                    config, journal_path=f"{config.journal_path}.r{i}"
                )
            self._replica_cfgs.append(rcfg)
            self.replicas.append(PlannerReplica(i, Extender(
                rcfg, clock=clock,
                eviction_sink=self.pending_evictions,
            )))
        self._n = n
        # N=1 parity gate: every entry point delegates VERBATIM to the
        # sole replica's Extender (same objects, same code path)
        self._sole = self.replicas[0].extender if n == 1 else None
        # router maps only (replica state lives behind each replica's
        # own locks; this leaf lock never nests around them on the
        # mutation path — routing reads replica state lock-free
        # through the epoch-cached snapshots)
        self._lock = threading.RLock()
        self._slice_replica: dict[str, int] = {}
        self._node_replica: dict[str, int] = {}
        self._pod_replica: dict[str, int] = {}
        self._gang_replica: dict[tuple[str, str], int] = {}
        self._dcn: dict[tuple[str, str], _Rendezvous] = {}
        # driver-admitted pods whose owner replica found them
        # unschedulable: attempt counts rotate the next admit to the
        # following replica (the webhook path spills over inline; the
        # admit path has no answer to spill on). Entries retire at
        # bind/release.
        self._pod_attempts: dict[str, int] = {}
        # last scheduling-clock instant the rendezvous janitor ran
        # from the gang-routing path (throttle; see _route_gang)
        self._swept_at: Optional[float] = None
        # rendezvous aborted while participants were unreachable:
        # key -> the replica indices that could NOT be dissolved at
        # abort time. A healed/restarted participant still on the list
        # has its leftover fragment dissolved (even a locally-committed
        # one — death is all-or-nothing), then leaves the list; the
        # key retires when the list empties. Scoping the sentence to
        # the EXACT unreachable replicas means a same-named gang
        # re-created meanwhile on other replicas is never touched.
        self._aborted_dcn: dict[tuple[str, str], set[int]] = {}
        # counters (per-replica metrics/statusz)
        self.rendezvous_prepared = 0
        self.rendezvous_committed = 0
        self.rendezvous_aborted = 0
        self.state = _FederatedState(self)
        self.cycle = (_RouterCycle(self)
                      if config.batch_enabled else None)
        self.events = _MergedEvents(self)
        self.trace = None
        self.journal = None
        self.decisions = None

    # -- Extender-surface passthroughs --------------------------------------
    @property
    def evict_precheck(self):
        return self.replicas[0].extender.evict_precheck

    @evict_precheck.setter
    def evict_precheck(self, fn) -> None:
        for rep in self.replicas:
            rep.extender.evict_precheck = fn

    @property
    def binder(self):
        return self.replicas[0].extender.binder

    @binder.setter
    def binder(self, fn) -> None:
        for rep in self.replicas:
            rep.extender.binder = fn

    @property
    def degraded_gate(self):
        return self.replicas[0].extender.degraded_gate

    @degraded_gate.setter
    def degraded_gate(self, fn) -> None:
        for rep in self.replicas:
            rep.extender.degraded_gate = fn

    @property
    def latencies(self) -> dict[str, list[float]]:
        """Merged webhook-latency windows (quantile feeds)."""
        out: dict[str, list[float]] = {}
        for rep in self.replicas:
            for handler, window in rep.extender.latencies.items():
                out.setdefault(handler, []).extend(window)
        return out

    @property
    def preemptions(self) -> int:
        return sum(r.extender.preemptions for r in self.replicas)

    @property
    def binds_total(self) -> int:
        return sum(r.extender.binds_total for r in self.replicas)

    def gang_snapshot(self) -> list[dict[str, Any]]:
        out: list[dict[str, Any]] = []
        for rep in self.replicas:
            if rep.killed:
                continue  # a dead shard's reservations died with it
            out.extend(rep.extender.gang_snapshot())
        return sorted(out, key=lambda g: (g["namespace"], g["group"]))

    def alloc_snapshot(self) -> list[dict[str, Any]]:
        out: list[dict[str, Any]] = []
        for rep in self.replicas:
            if rep.killed:
                continue
            out.extend(rep.extender.alloc_snapshot())
        return sorted(out, key=lambda a: a["pod"])

    def audit_stats(self) -> dict[str, Any]:
        """Summed snapshot-audit sentinel counters across replicas."""
        rate = max(
            (r.extender.snapshots.audit_rate for r in self.replicas),
            default=0.0,
        )
        return {
            "rate": rate,
            "checks": sum(r.extender.snapshots.audit_checks
                          for r in self.replicas),
            "divergences": sum(r.extender.snapshots.audit_divergences
                               for r in self.replicas),
        }

    def statusz(self) -> dict[str, Any]:
        """The router's /statusz section: topology + rendezvous state +
        one summary row per replica (the per-replica observability leg
        of the sharded plane; each replica's full extender_statusz
        stays available on its own listener in a real deployment)."""
        with self._lock:
            rendezvous = [
                {
                    "gang": f"{key[0]}/{key[1]}",
                    "committed": rdv.committed,
                    "parts": {
                        self.replicas[idx].name: {
                            sid: len(coords)
                            for sid, coords in parts.items()
                        }
                        for idx, parts in rdv.parts.items()
                    },
                }
                for key, rdv in sorted(self._dcn.items())
            ]
            slice_map = {
                sid: self.replicas[idx].name
                for sid, idx in sorted(self._slice_replica.items())
            }
        per_replica = []
        for rep in self.replicas:
            ext = rep.extender
            st = ext.state
            used = total = 0
            for sid in st.slice_ids():
                u, t = st.slice_share_counts(sid)
                used += u
                total += t
            per_replica.append({
                "replica": rep.name,
                "alive": rep.alive,
                "slices": st.slice_ids(),
                "nodes": len(st.node_names()),
                "allocs": len(st.allocations()),
                "pods_routed": rep.pods_routed,
                "binds_total": ext.binds_total,
                "utilization": round(used / total, 4) if total else 0.0,
                "queue_depth": (ext.cycle.queue_depth()
                                if ext.cycle is not None else 0),
                "snapshot_hits": ext.snapshots.hits,
                "snapshot_rebuilds": ext.snapshots.rebuilds,
            })
        return {
            "replicas": per_replica,
            "slice_assignment": slice_map,
            "rendezvous": {
                "live": rendezvous,
                "prepared": self.rendezvous_prepared,
                "committed": self.rendezvous_committed,
                "aborted": self.rendezvous_aborted,
            },
        }

    # -- slice / node / pod assignment --------------------------------------
    def _slice_of_payload(self, annotations: dict[str, str]) -> Optional[str]:
        payload = annotations.get(codec.ANNO_NODE_TOPOLOGY)
        if not payload:
            return None
        try:
            obj = json.loads(payload)
        except (TypeError, ValueError):
            return None
        sid = obj.get("slice")
        return sid if isinstance(sid, str) and sid else None

    def _assign_slice_locked(self, sid: str) -> int:
        """Deterministic least-loaded slice→replica assignment: a new
        slice goes to the replica owning the fewest slices (ties break
        on index), so a fleet whose slices register in sorted order —
        the sim and any annotation-synced cluster — balances exactly.
        Recorded in the router map; a production deployment pins the
        same assignment in per-replica config."""
        idx = self._slice_replica.get(sid)
        if idx is None:
            counts = [0] * self._n
            for i in self._slice_replica.values():
                counts[i] += 1
            idx = min(range(self._n), key=lambda i: (counts[i], i))
            self._slice_replica[sid] = idx
            log.info("slice %s assigned to replica %s", sid,
                     self.replicas[idx].name)
        return idx

    def _replica_for_node(
        self, name: str, annotations: Optional[dict[str, str]] = None
    ) -> Optional[int]:
        with self._lock:
            idx = self._node_replica.get(name)
            if idx is not None:
                return idx
            if annotations is None:
                return None
            sid = self._slice_of_payload(annotations)
            if sid is None:
                return None
            idx = self._assign_slice_locked(sid)
            self._node_replica[name] = idx
            return idx

    def _alive(self) -> list[PlannerReplica]:
        return [r for r in self.replicas if r.alive]

    def _hash_replica(self, pod_key: str) -> int:
        return zlib.crc32(pod_key.encode("utf-8")) % self._n

    def _pick_pod_replica(self, pod_key: str,
                          attempts: Optional[int] = None) -> int:
        """Stable hash with liveness fallback: the hash spreads the
        burst plane uniformly; a dead primary falls over to the next
        alive index. Spillover on a FULL primary: the webhook path
        retries the other replicas inline (filter answers), the admit
        path rotates by the pod's recorded failed-plan attempts
        (pass ``attempts`` pre-read to save a lock round-trip on the
        driver hot path — there is ONE rotation policy, not two)."""
        if attempts is None:
            with self._lock:
                attempts = self._pod_attempts.get(pod_key, 0)
        primary = self._hash_replica(pod_key) + attempts
        for off in range(self._n):
            idx = (primary + off) % self._n
            if self.replicas[idx].alive:
                return idx
        raise ShardError("no alive planner replica")

    # -- node partitioning for webhook bodies --------------------------------
    def _partition_nodes(
        self, nodes: list[dict[str, Any]]
    ) -> dict[int, list[dict[str, Any]]]:
        """Split a raw-node webhook body per owning replica (unknown
        names — nodes never annotated — are dropped from every part).
        Only the RAW mode partitions: a replica must never ingest
        another shard's node objects. Names-only bodies forward
        verbatim — the target replica answers its own nodes and
        reports the rest infeasible, which is both correct and O(1)
        under plan-served filter answers (re-partitioning 10k names
        per webhook was a measured router tax)."""
        parts: dict[int, list[dict[str, Any]]] = {}
        for obj in nodes:
            name, annotations = kube.node_name_and_annotations(obj)
            idx = self._replica_for_node(name, annotations)
            if idx is None:
                continue
            parts.setdefault(idx, []).append(obj)
        return parts

    # -- gang routing + two-phase rendezvous ---------------------------------
    def _gang_chips(self, pod: PodInfo) -> Optional[tuple[int, int]]:
        """(chips_per_pod, total chips) for a gang pod, None when the
        request is malformed (the home replica reports the schema
        error exactly as the unsharded path would)."""
        try:
            ask = Extender.device_request(pod)
        except ExtenderError:
            return None  # the routed replica reports the schema error
        if ask is None or pod.group is None:
            return None
        return ask[1], ask[1] * pod.group.min_member

    def _replica_fits_gang(self, rep: PlannerReplica, pod: PodInfo,
                           total: int) -> bool:
        """Can this replica host the gang ICI-contiguously in ONE of
        its slices? Same search ``ensure_reservation`` runs — against
        the replica's epoch-cached snapshot, so the sweep this builds
        is the sweep the reservation reuses."""
        snap = rep.extender.snapshots.current()
        shape = pod.group.shape if pod.group is not None else None
        for sid in snap.slice_ids():
            ss = snap.slice(sid)
            if ss.blocked_free_chips < total:
                continue
            coords = slicefit.find_slice_in(
                ss.blocked_sweep(),
                count=None if shape is not None else total,
                shape=shape,
                broken=ss.broken,
            )
            if coords is not None:
                return True
        return False

    def _route_gang(self, pod: PodInfo) -> int:
        """The gang pod's target replica: its rendezvous participant
        with room, its established home, or — for a new gang — the
        first replica that fits it whole; a gang that fits nowhere and
        opted into DCN gets the two-phase rendezvous. Falls back to
        the emptiest alive replica so error answers (config mistakes,
        genuinely unschedulable gangs) come from a deterministic
        place."""
        assert pod.group is not None
        key = (pod.namespace, pod.group.name)
        # the janitor runs at most once per scheduling-clock instant:
        # a 512-member gang admitted in one batch (one FakeClock tick,
        # one webhook burst) must not pay 512 full rendezvous sweeps —
        # plan_pending() additionally sweeps once per drive
        now = self.clock.monotonic()
        if now != self._swept_at:
            self._swept_at = now
            self.sweep()
        with self._lock:
            rdv = self._dcn.get(key)
        if rdv is not None:
            idx = self._rendezvous_member_target(rdv, pod)
            if idx is not None:
                return idx
            # every part full: overflow replica — any participant
            # answers it as a normal pod (assignable() is False there)
            for idx in rdv.parts:
                if self.replicas[idx].alive:
                    return idx
        with self._lock:
            home = self._gang_replica.get(key)
        if home is not None and self.replicas[home].alive \
                and self.replicas[home].extender.gang.reservation(
                    *key) is not None:
            # sticky only while the home actually HOLDS a reservation:
            # a gang that transiently fit nowhere must re-probe the
            # whole fleet (and the rendezvous) on every retry, not
            # stay pinned to whichever replica owned the error answer
            return home
        ask = self._gang_chips(pod)
        ranked = sorted(
            self._alive(),
            key=lambda r: (self.state_utilization_of(r), r.index),
        )
        if not ranked:
            raise ShardError("no alive planner replica")
        if home is not None and self.replicas[home].alive:
            # prefer the previous home when it still fits — re-probing
            # must not flip a mid-reserve gang between replicas
            ranked.sort(key=lambda r: r.index != home)
        if ask is not None:
            cpp, total = ask
            for rep in ranked:
                if self._replica_fits_gang(rep, pod, total):
                    with self._lock:
                        self._gang_replica[key] = rep.index
                    return rep.index
            if pod.group.allow_dcn and pod.group.shape is None \
                    and self._n > 1:
                rdv = self._prepare_rendezvous(pod, cpp, total)
                if rdv is not None:
                    idx = self._rendezvous_member_target(rdv, pod)
                    if idx is not None:
                        return idx
        # nothing fits anywhere (or the request is malformed): the
        # emptiest replica owns the error answer; NOT recorded as a
        # sticky home — the next retry re-probes a changed fleet
        return ranked[0].index

    def state_utilization_of(self, rep: PlannerReplica) -> float:
        """One replica's used-share fraction off its cached snapshot
        (O(slices) — never a ledger walk on the routing path)."""
        snap = rep.extender.snapshots.current()
        used = total = 0
        for sid in snap.slice_ids():
            ss = snap.slice(sid)
            used += ss.used_shares
            total += ss.total_shares
        return used / total if total else 0.0

    def _rendezvous_member_target(
        self, rdv: _Rendezvous, pod: PodInfo
    ) -> Optional[int]:
        """The participant replica this member filters, scores, AND
        binds on: sticky per pod (every webhook of one member must
        land on the part holding its chips), parts filling in
        replica-index order, each capped at its local quorum — the
        driver path admits every member before any binds, so the
        reservation's own room cannot spread them."""
        with self._lock:
            idx = rdv.member_target.get(pod.key())
            if idx is not None and self.replicas[idx].alive:
                return idx
            routed: dict[int, int] = {}
            for i in rdv.member_target.values():
                routed[i] = routed.get(i, 0) + 1
            for i in sorted(rdv.parts):
                if not self.replicas[i].alive:
                    continue
                if routed.get(i, 0) < rdv.local_min.get(i, 0):
                    rdv.member_target[pod.key()] = i
                    return i
        return None

    def _prepare_rendezvous(
        self, pod: PodInfo, cpp: int, total: int
    ) -> Optional[_Rendezvous]:
        """Phases 1+2 of the rendezvous (see module docstring): plan
        per-replica contiguous parts greedily, PREPARE each part as a
        local reservation, and commit the rendezvous record — or abort
        every prepared part on the first failure. None = the fleet
        cannot cover the gang; the caller serves the home replica's
        no-slice error and the scheduler retries later."""
        assert pod.group is not None
        key = (pod.namespace, pod.group.name)
        # PLAN: greedy over (replica, slice) by emptiness — one box per
        # slice, each a multiple of chips_per_pod, largest first (the
        # cross-replica mirror of GangManager._plan_dcn_split)
        candidates: list[tuple[float, str, int, Any]] = []
        for rep in self._alive():
            snap = rep.extender.snapshots.current()
            for sid in snap.slice_ids():
                ss = snap.slice(sid)
                candidates.append((ss.utilization, sid, rep.index, ss))
        candidates.sort(key=lambda c: (c[0], c[1]))
        parts: dict[int, dict[str, list[TopologyCoord]]] = {}
        remaining = total
        for _, sid, idx, ss in candidates:
            if remaining == 0:
                break
            vol = min(remaining, (ss.blocked_free_chips // cpp) * cpp)
            while vol >= cpp:
                coords = slicefit.find_slice_in(
                    ss.blocked_sweep(), count=vol, broken=ss.broken
                )
                if coords is not None:
                    parts.setdefault(idx, {})[sid] = list(coords)
                    remaining -= len(coords)
                    break
                vol -= cpp
        if remaining != 0 or len(parts) < 2:
            # len(parts) < 2 cannot happen when every single replica
            # already failed the whole-gang fit — defensive: a
            # one-replica "rendezvous" is just that replica's own
            # _plan_dcn_split, which its ensure_reservation will run
            return None
        # PREPARE each part under its replica's own locks; roll back
        # every prepared part on the first failure (no members have
        # bound, so drop_reservation — not dissolve — is the abort)
        prepared: list[int] = []
        local_min: dict[int, int] = {}
        for idx in sorted(parts):
            rep = self.replicas[idx]
            members = sum(len(c) for c in parts[idx].values()) // cpp
            local_min[idx] = members
            local_pod = dc_replace(pod, group=PodGroup(
                name=pod.group.name, min_member=members,
                shape=None, allow_dcn=True,
            ))
            try:
                rep.extender.gang.reserve_exact_split(
                    local_pod, cpp, parts[idx]
                )
            except Exception as e:
                # any prepare failure aborts every prepared part (no
                # members have bound, so drop — not dissolve); only
                # the EXPECTED races (box re-occupied, slice gone)
                # degrade to "retry next cycle" — anything else is a
                # bug and re-raises after the abort
                log.warning(
                    "rendezvous %s/%s: prepare on %s failed (%s); "
                    "aborting %d prepared part(s)",
                    key[0], key[1], rep.name, e, len(prepared),
                )
                for pidx in prepared:
                    self.replicas[pidx].extender.gang.drop_reservation(
                        key
                    )
                with self._lock:
                    self.rendezvous_aborted += 1
                if not isinstance(e, (GangError, StateError)):
                    raise
                return None
            prepared.append(idx)
        rdv = _Rendezvous(key, parts, local_min,
                          created=self.clock.monotonic())
        with self._lock:
            self._dcn[key] = rdv
            self.rendezvous_prepared += 1
        self.events.emit(
            "GangReserved", obj=f"gang/{key[0]}/{key[1]}",
            message=(
                f"two-phase rendezvous prepared: {total} chips over "
                f"{sum(len(p) for p in parts.values())} slice part(s) "
                f"on {len(parts)} replica(s)"
            ),
        )
        log.info(
            "rendezvous %s/%s prepared: %d chips over replicas %s",
            key[0], key[1], total,
            {self.replicas[i].name: sorted(p) for i, p in parts.items()},
        )
        return rdv

    def sweep(self) -> list[tuple[str, str]]:
        """The rendezvous janitor (phase 3's abort half), run at the
        top of every gang routing and every batch drive: sweep each
        participant's local TTL/fault janitor, then enforce
        all-or-nothing — an uncommitted rendezvous that lost ANY part
        (TTL rollback, fault, replica killed/partitioned) dissolves
        its surviving parts, evicting their bound members through the
        shared eviction bus. A COMMITTED rendezvous tolerates a dead
        replica: its part is durable in pod annotations and restores
        with the replica. Returns the aborted gang keys."""
        aborted: list[tuple[str, str]] = []
        with self._lock:
            live = list(self._dcn.items())
        for key, rdv in live:
            held: list[tuple[int, Any]] = []
            lost = False
            for idx in rdv.parts:
                rep = self.replicas[idx]
                if not rep.alive:
                    if not rdv.committed:
                        lost = True
                    continue
                rep.extender.gang.sweep()
                res = rep.extender.gang.reservation(*key)
                if res is None:
                    lost = True
                else:
                    held.append((idx, res))
            if not rdv.committed and held and not lost \
                    and all(res.committed for _, res in held) \
                    and len(held) == len(rdv.parts):
                self._check_rendezvous_commit(rdv)
                continue
            if lost and not rdv.committed:
                for idx, _res in held:
                    self.replicas[idx].extender.gang.dissolve(key)
                unreachable = {
                    idx for idx in rdv.parts
                    if not self.replicas[idx].alive
                }
                with self._lock:
                    self._dcn.pop(key, None)
                    self._gang_replica.pop(key, None)
                    if unreachable:
                        self._aborted_dcn.setdefault(
                            key, set()).update(unreachable)
                    self.rendezvous_aborted += 1
                aborted.append(key)
                self.events.emit(
                    "GangRollback", obj=f"gang/{key[0]}/{key[1]}",
                    message=(
                        "rendezvous aborted: a part was lost before "
                        "commit (TTL/fault/replica down); surviving "
                        "parts dissolved all-or-nothing"
                    ), type="Warning",
                )
                log.warning("rendezvous %s/%s aborted (part lost "
                            "pre-commit)", key[0], key[1])
            elif not held and rdv.committed and all(
                self.replicas[idx].alive for idx in rdv.parts
            ):
                # every part released naturally (members finished):
                # the rendezvous record retires
                with self._lock:
                    self._dcn.pop(key, None)
                    self._gang_replica.pop(key, None)
        # retire gang-home entries whose reservation is gone (the gang
        # completed or rolled back): routing already re-probes on a
        # missing reservation, so this is purely the memory bound —
        # unbounded unique gang names must not grow the map forever
        with self._lock:
            homes = [(k, i) for k, i in self._gang_replica.items()
                     if k not in self._dcn]
        for key, idx in homes:
            rep = self.replicas[idx]
            if rep.alive \
                    and rep.extender.gang.reservation(*key) is None:
                with self._lock:
                    if self._gang_replica.get(key) == idx \
                            and key not in self._dcn:
                        self._gang_replica.pop(key, None)
        return aborted

    # -- the decision surface -------------------------------------------------
    def handle(self, kind: str, body: Any) -> Any:
        if self._sole is not None:
            return self._sole.handle(kind, body)
        if kind in ("filter", "prioritize"):
            return self._handle_scoring(kind, body)
        if kind == "bind":
            return self._handle_bind(body)
        if kind == "release":
            return self._handle_release(body)
        if kind == "victim_gone":
            cleared = False
            for rep in self._alive():
                out = rep.extender.handle(kind, body)
                cleared = cleared or bool(out.get("cleared"))
            return {"cleared": cleared}
        if kind == "reconcile":
            changed = False
            for rep in self._alive():
                if rep.extender.state.allocation(body["pod_key"]) is None:
                    continue
                out = rep.extender.handle(kind, body)
                changed = changed or bool(out.get("changed"))
            return {"changed": changed}
        if kind == "upsert_node":
            idx = self._replica_for_node(
                body["name"], dict(body.get("annotations") or {})
            )
            if idx is None:
                return {"ours": False}
            if not self.replicas[idx].alive:
                return {"error": f"replica {self.replicas[idx].name} "
                                 f"unavailable"}
            return self.replicas[idx].extender.handle(kind, body)
        raise ValueError(f"unknown decision kind {kind!r}")

    def _handle_release(self, body: Any) -> Any:
        pod_key = body["pod_key"]
        with self._lock:
            idx = self._pod_replica.pop(pod_key, None)
            self._pod_attempts.pop(pod_key, None)
        targets = (
            [self.replicas[idx]] if idx is not None
            else list(self.replicas)
        )
        for rep in targets:
            if not rep.alive:
                # a dead replica's release is lost exactly like a real
                # crashed daemon's: the restart rebuild (killed) or the
                # post-heal lifecycle resync (partitioned) re-converges
                # against the pod store
                continue
            rep.extender.handle("release", {"pod_key": pod_key})
        return None

    def _handle_scoring(self, kind: str, body: Any) -> Any:
        pod, nodes, names = kube.parse_extender_args(body)
        parts: Optional[dict[int, list]] = None
        if nodes is not None:
            parts = self._partition_nodes(nodes)
            # every owning replica ingests its node objects NOW (the
            # webhook is how topology reaches the caches; only the
            # target replica gets the scoring call, but a later
            # spillover to another replica must find its nodes known).
            # payload_matches makes the unchanged-resend case cheap.
            for idx, pnodes in parts.items():
                rep = self.replicas[idx]
                if not rep.alive:
                    continue
                for obj in pnodes:
                    name, annotations = kube.node_name_and_annotations(
                        obj
                    )
                    try:
                        rep.extender.state.upsert_node(name, annotations)
                    except Exception:
                        log.exception("node %s rejected by %s at "
                                      "ingest", name, rep.name)
        bad_ask = False
        try:
            ask = Extender.device_request(pod)
        except ExtenderError:
            # malformed request (e.g. both TPU and vTPU asked): MUST
            # route to a replica so its handler reports the schema
            # error exactly like the unsharded planner — the non-TPU
            # fast exit below would silently answer it feasible
            # everywhere
            ask = None
            bad_ask = True
        if ask is None and pod.group is None and not bad_ask:
            # non-TPU pod: feasible everywhere, tracked nowhere — no
            # replica needs to see it (matches the unsharded fast exit)
            if kind == "prioritize":
                return kube.host_priority_list(
                    {n: 0 for n in (names or [])}
                )
            if nodes is not None:
                return kube.filter_result(list(nodes), {})
            return kube.filter_result_names(list(names or []), {})
        if pod.group is not None:
            idx = self._route_gang(pod)
        else:
            with self._lock:
                idx = self._pod_replica.get(pod.key())
            if idx is None or not self.replicas[idx].alive:
                idx = self._pick_pod_replica(pod.key())
        return self._score_on(kind, body, pod, parts, idx)

    @staticmethod
    def _sub_body(body: Any, parts: Optional[dict[int, list]],
                  idx: int) -> dict:
        """The body replica ``idx`` sees: its own node objects in raw
        mode; the verbatim body otherwise (a names-only replica
        answers foreign names infeasible on its own — correct, and
        O(1) under plan-served answers)."""
        if parts is None:
            return body
        sub = dict(body)
        sub["Nodes"] = {"Items": parts.get(idx, [])}
        sub.pop("NodeNames", None)
        return sub

    def _score_on(self, kind: str, body: Any, pod: PodInfo,
                  parts: Optional[dict[int, list]], idx: int) -> Any:
        """Forward a filter/prioritize to replica ``idx``. For a
        non-gang filter, spill over to the other alive replicas
        (emptiest first) when the target answers nothing feasible —
        slice affinity routes, the fleet answers. Nodes on other
        shards simply stay out of the feasible set (the upstream
        protocol prunes whatever the answer omits)."""
        def spill_order():
            # built lazily: the common primary-feasible case must not
            # pay O(replicas x slices) utilization reads per webhook
            yield idx
            if kind != "filter" or pod.group is not None:
                return
            for r in sorted(
                self._alive(),
                key=lambda r: (self.state_utilization_of(r), r.index),
            ):
                if r.index != idx:
                    yield r.index

        last_out: Any = None
        for i in spill_order():
            rep = self.replicas[i]
            if not rep.alive or (parts is not None and i not in parts):
                continue
            out = rep.extender.handle(
                kind, self._sub_body(body, parts, i)
            )
            if kind == "prioritize":
                return out  # scores for the target's own nodes
            feasible_names = out.get("NodeNames") or []
            last_out = out
            if feasible_names and not out.get("Error"):
                with self._lock:
                    self._pod_replica[pod.key()] = i
                rep.pods_routed += 1
                return out
        if last_out is not None:
            return last_out
        if kind == "prioritize":
            return kube.host_priority_list({})
        mk = (kube.filter_result if parts is not None
              else kube.filter_result_names)
        return mk([], {}, error="no alive planner replica owns any "
                                "offered node")

    def _handle_bind(self, body: Any) -> Any:
        name, ns, uid, node = kube.parse_binding_args(body)
        key = f"{ns}/{name}"
        with self._lock:
            idx = self._node_replica.get(node)
            if idx is None:
                idx = self._pod_replica.get(key)
        if idx is None:
            return kube.binding_result(
                f"{key}: node {node} is owned by no planner replica"
            )
        rep = self.replicas[idx]
        if not rep.alive:
            return kube.binding_result(
                f"{key}: replica {rep.name} unavailable (partitioned "
                f"or restarting); scheduler will retry"
            )
        out = rep.extender.handle("bind", body)
        if isinstance(out, dict) and not out.get("Error"):
            with self._lock:
                self._pod_replica[key] = idx
                self._pod_attempts.pop(key, None)
                rdv = next(
                    (r for r in self._dcn.values()
                     if key in r.member_target), None,
                )
            if rdv is not None:
                self._globalize_gang_env(out, rdv)
                # EAGER commit check at the bind that may have closed
                # the last part's quorum: waiting for the next janitor
                # sweep leaves a window where a replica killed after
                # the final bind reads as "part lost pre-commit" and
                # the janitor dissolves a fully-committed gang
                self._check_rendezvous_commit(rdv)
        return out

    def _check_rendezvous_commit(self, rdv: _Rendezvous) -> None:
        """Flip the rendezvous to committed the moment every part's
        local reservation is committed (idempotent; also run by the
        janitor sweep for the webhook-paced path)."""
        if rdv.committed:
            return
        for idx in rdv.parts:
            rep = self.replicas[idx]
            if not rep.alive:
                return
            res = rep.extender.gang.reservation(*rdv.key)
            if res is None or not res.committed:
                return
        rdv.committed = True
        with self._lock:
            self.rendezvous_committed += 1
        self.events.emit(
            "GangCommitted", obj=f"gang/{rdv.key[0]}/{rdv.key[1]}",
            message=(f"rendezvous committed: all {len(rdv.parts)} "
                     f"parts assembled"),
        )

    def _globalize_gang_env(self, out: dict, rdv: _Rendezvous) -> None:
        """A rendezvous member's bind answer carries the TPU_KUBE_GANG_*
        env of its LOCAL part (the replica only knows its own slices);
        rewrite the annotation to the GLOBAL rendezvous topology so the
        in-pod runtime forms the full multislice collective — the same
        contract a single-planner DCN gang's bind stamps."""
        from tpukube.device.tpu import (
            ENV_GANG_NUM_SLICES,
            ENV_GANG_SLICE_INDEX,
            ENV_GANG_SLICES,
        )

        payload = (out.get("Annotations") or {}).get(codec.ANNO_ALLOC)
        if not payload:
            return
        try:
            alloc = codec.decode_alloc(payload)
        except codec.CodecError:
            return
        # the pod's OWN slice comes from its local index into the
        # part's local slice list — a part may span several slices,
        # so the first local slice is NOT every member's slice
        local_sids = [s for s in
                      alloc.env.get(ENV_GANG_SLICES, "").split(",") if s]
        try:
            local_idx = int(alloc.env.get(ENV_GANG_SLICE_INDEX, ""))
            local_sid = local_sids[local_idx]
        except (ValueError, IndexError):
            return
        sids = sorted({
            sid for parts in rdv.parts.values() for sid in parts
        })
        if local_sid not in sids:
            return
        env = dict(alloc.env)
        env[ENV_GANG_NUM_SLICES] = str(len(sids))
        env[ENV_GANG_SLICES] = ",".join(sids)
        env[ENV_GANG_SLICE_INDEX] = str(sids.index(local_sid))
        out["Annotations"][codec.ANNO_ALLOC] = codec.encode_alloc(
            dc_replace(alloc, env=env)
        )

    # -- batch-driver surface -------------------------------------------------
    def admit(self, pod: PodInfo) -> bool:
        if self._sole is not None:
            return self._sole.admit(pod)
        key = pod.key()
        if pod.group is not None:
            idx = self._route_gang(pod)
        else:
            # one lock round-trip for the whole routing read (this is
            # the per-pod driver hot path)
            with self._lock:
                idx = self._pod_replica.get(key)
                attempts = self._pod_attempts.get(key, 0)
            if idx is None or not self.replicas[idx].alive:
                idx = self._pick_pod_replica(key, attempts)
        rep = self.replicas[idx]
        if not rep.alive:
            return False
        ok = rep.extender.admit(pod)
        if ok:
            with self._lock:
                self._pod_replica[key] = idx
            rep.pods_routed += 1
        return ok

    def plan_pending(self) -> int:
        if self._sole is not None:
            return self._sole.plan_pending()
        self.sweep()
        return sum(
            rep.extender.plan_pending() for rep in self._alive()
        )

    def planned_node(self, pod_key: str) -> Optional[str]:
        if self._sole is not None:
            return self._sole.planned_node(pod_key)
        with self._lock:
            idx = self._pod_replica.get(pod_key)
        if idx is not None and self.replicas[idx].alive:
            node = self.replicas[idx].extender.planned_node(pod_key)
            if node is not None:
                return node
            # plan failed or expired on the owner: release the
            # affinity and bump the attempt count so the next admit
            # rotates to another replica instead of re-queuing on the
            # same full shard forever
            with self._lock:
                if self._pod_replica.get(pod_key) == idx:
                    self._pod_replica.pop(pod_key, None)
                self._pod_attempts[pod_key] = \
                    self._pod_attempts.get(pod_key, 0) + 1
            return None
        for rep in self._alive():
            node = rep.extender.planned_node(pod_key)
            if node is not None:
                return node
        return None

    def release(self, pod_key: str) -> None:
        self.handle("release", {"pod_key": pod_key})

    # -- restart / recovery ---------------------------------------------------
    def rebuild_from_pods(self, pods: list[dict[str, str]]) -> int:
        """Cold rebuild across the partition: pods route to the
        replica owning their bound node; the pod-group annotations of
        a COMMITTED DCN-rendezvous gang (members spanning >1 replica,
        quorum present) are rewritten to each part's LOCAL member
        count so every part restores committed-verbatim — the
        rendezvous record itself is then re-registered. A PARTIAL
        DCN gang restores with its original annotations, so each part
        rolls its members back: all-or-nothing in death, exactly the
        single-planner restore contract."""
        if self._sole is not None:
            return self._sole.rebuild_from_pods(pods)
        by_replica: dict[int, list[dict[str, str]]] = {}
        gangs: dict[tuple[str, str], list[tuple[int, dict, Any]]] = {}
        skipped = 0
        for annotations in pods:
            payload = annotations.get(codec.ANNO_ALLOC)
            if not payload:
                continue
            try:
                alloc = codec.decode_alloc(payload)
            except codec.CodecError:
                skipped += 1
                continue
            idx = self._replica_for_node(alloc.node_name)
            if idx is None:
                log.error("rebuild: %s bound to unmapped node %s; "
                          "skipped", alloc.pod_key, alloc.node_name)
                skipped += 1
                continue
            by_replica.setdefault(idx, []).append(annotations)
            try:
                group = codec.pod_group_from_annotations(annotations)
            except codec.CodecError:
                group = None
            if group is not None:
                ns = alloc.pod_key.split("/", 1)[0]
                gangs.setdefault((ns, group.name), []).append(
                    (idx, annotations, group)
                )
        rewrites: dict[tuple[str, str], dict[int, int]] = {}
        for key, members in gangs.items():
            replicas_of = {idx for idx, _, _ in members}
            group = members[0][2]
            if len(replicas_of) > 1 and len(members) >= group.min_member:
                # committed DCN gang: each part restores by its LOCAL
                # quorum (the full min_member would read as partial
                # everywhere and roll a healthy gang back)
                counts: dict[int, int] = {}
                for idx, _, _ in members:
                    counts[idx] = counts.get(idx, 0) + 1
                rewrites[key] = counts
                for idx, annotations, g in members:
                    annotations.update(codec.pod_group_annotations(
                        PodGroup(name=g.name,
                                 min_member=counts[idx],
                                 shape=None, allow_dcn=True)
                    ))
        restored = 0
        for idx, plist in sorted(by_replica.items()):
            restored += self.replicas[idx].extender.rebuild_from_pods(
                plist
            )
            with self._lock:
                for annotations in plist:
                    payload = annotations.get(codec.ANNO_ALLOC)
                    if payload:
                        try:
                            alloc = codec.decode_alloc(payload)
                        except codec.CodecError:
                            continue
                        self._pod_replica[alloc.pod_key] = idx
        for key, counts in rewrites.items():
            parts: dict[int, dict[str, list[TopologyCoord]]] = {}
            for idx in counts:
                res = self.replicas[idx].extender.gang.reservation(*key)
                if res is not None:
                    parts[idx] = {
                        sid: sorted(coords)
                        for sid, coords in res.slice_coords.items()
                    }
            if len(parts) > 1:
                rdv = _Rendezvous(
                    key, parts,
                    {idx: counts[idx] for idx in parts},
                    created=self.clock.monotonic(),
                )
                rdv.committed = True
                with self._lock:
                    self._dcn[key] = rdv
        return restored

    def replica_pods(self, idx: int,
                     pods: dict[str, dict[str, Any]]) -> list[dict]:
        """The pod store entries bound to replica ``idx``'s nodes (the
        harness's per-replica restart feed)."""
        out = []
        with self._lock:
            owned = {n for n, i in self._node_replica.items()
                     if i == idx}
        for pod in pods.values():
            node = (pod.get("spec") or {}).get("nodeName")
            if node in owned:
                out.append(pod)
        return out

    def kill_replica(self, idx: int) -> None:
        """Model replica process death: everything in-memory on the
        shard — ledger, reservations, queue, plans — is gone; nothing
        is flushed. The router keeps routing around it, the federated
        read views stop serving the corpse's ledger (``killed``), and
        the rendezvous janitor aborts any uncommitted rendezvous
        holding a part there."""
        rep = self.replicas[idx]
        rep.alive = False
        rep.killed = True
        if rep.extender.journal is not None:
            rep.extender.journal.crash()
        rep.extender.state.retire()

    def partition_replica(self, idx: int) -> None:
        """Model a network partition: the replica's state survives but
        the router cannot reach it — scoring/bind answers route
        around or fail retryably, and an uncommitted rendezvous part
        there counts as lost (all-or-nothing abort)."""
        self.replicas[idx].alive = False

    def heal_replica(self, idx: int) -> None:
        """End a partition: the replica serves again with the state it
        kept — MINUS any fragment of a rendezvous the janitor aborted
        while THIS replica was unreachable (a locally-complete part of
        a dead gang must die all-or-nothing, not resurrect as a
        fragment). The sentence is scoped to the exact replicas that
        were unreachable at abort time, so a same-named gang
        re-created meanwhile on other replicas is never touched.
        Other reservations resolve through the normal janitors."""
        rep = self.replicas[idx]
        rep.alive = True
        self._settle_aborted_parts(idx)

    def _settle_aborted_parts(self, idx: int) -> None:
        """Dissolve replica ``idx``'s leftover fragments of rendezvous
        aborted while it was unreachable, and retire it from every
        pending sentence (heal AND restart both come through here —
        either way the replica's state is now reconciled)."""
        rep = self.replicas[idx]
        with self._lock:
            owed = [key for key, pending in self._aborted_dcn.items()
                    if idx in pending]
        for key in owed:
            if rep.extender.gang.reservation(*key) is not None:
                log.warning(
                    "replica %s returned holding part of aborted "
                    "rendezvous %s/%s; dissolving", rep.name, *key,
                )
                rep.extender.gang.dissolve(key)
        with self._lock:
            for key in owed:
                pending = self._aborted_dcn.get(key)
                if pending is not None:
                    pending.discard(idx)
                    if not pending:
                        self._aborted_dcn.pop(key, None)

    def restart_replica(
        self, idx: int,
        node_annotations: list[tuple[str, dict[str, str]]],
        pods: list[dict[str, str]],
    ) -> int:
        """Cold-restart one killed replica the way a restarted shard
        daemon would: a fresh Extender, its nodes re-ingested, its
        ledger + gang reservations rebuilt from pod annotations
        (``rebuild_from_pods``), with live-rendezvous parts restored
        by their LOCAL quorum. Returns allocations restored."""
        old = self.replicas[idx]
        ext = Extender(
            self._replica_cfgs[idx], clock=self.clock,
            eviction_sink=self.pending_evictions,
        )
        # every externally-wired hook survives the restart (a fresh
        # daemon would be re-wired by its main; the router plays that
        # role here) — dropping the degraded gate would let ONE
        # restarted shard bind while the rest of the plane refuses
        ext.evict_precheck = old.extender.evict_precheck
        ext.binder = old.extender.binder
        ext.degraded_gate = old.extender.degraded_gate
        self.replicas[idx] = PlannerReplica(idx, ext)
        rep = self.replicas[idx]
        for name, annotations in node_annotations:
            out = ext.handle("upsert_node", {
                "name": name, "annotations": annotations,
            })
            if isinstance(out, dict) and out.get("error"):
                log.error("restart r%d: node %s rejected: %s",
                          idx, name, out["error"])
        with self._lock:
            live_rdv = {
                key: rdv for key, rdv in self._dcn.items()
                if idx in rdv.parts
            }
        plist: list[dict[str, str]] = []
        for annotations in pods:
            annotations = dict(annotations)
            try:
                group = codec.pod_group_from_annotations(annotations)
            except codec.CodecError:
                group = None
            if group is not None:
                # the rendezvous key is (namespace, group): an
                # unrelated same-named gang in ANOTHER namespace must
                # not have its quorum rewritten
                ns = None
                payload = annotations.get(codec.ANNO_ALLOC)
                if payload:
                    try:
                        ns = codec.decode_alloc(payload).pod_key.split(
                            "/", 1)[0]
                    except codec.CodecError:
                        ns = None
                rdv = (live_rdv.get((ns, group.name))
                       if ns is not None else None)
                if rdv is not None:
                    # this member belongs to a live rendezvous:
                    # restore its part by the LOCAL quorum
                    annotations.update(codec.pod_group_annotations(
                        PodGroup(name=group.name,
                                 min_member=rdv.local_min[idx],
                                 shape=None, allow_dcn=True)
                    ))
            plist.append(annotations)
        restored = ext.rebuild_from_pods(plist)
        with self._lock:
            for annotations in plist:
                payload = annotations.get(codec.ANNO_ALLOC)
                if payload:
                    try:
                        alloc = codec.decode_alloc(payload)
                    except codec.CodecError:
                        continue
                    self._pod_replica[alloc.pod_key] = idx
        rep.alive = True
        # a restored fragment of a rendezvous aborted while this
        # replica was down dies here (and the replica leaves the
        # pending sentence); then reconcile the rendezvous records
        # against what actually restored (an uncommitted part that
        # could not re-complete rolled back inside restore(); the
        # janitor then aborts the survivors — all-or-nothing)
        self._settle_aborted_parts(idx)
        self.sweep()
        return restored

    def shutdown(self) -> None:
        """Close every replica's sinks (harness stop path)."""
        for rep in self.replicas:
            ext = rep.extender
            if ext.trace is not None:
                ext.trace.close()
            ext.events.close()
            if ext.journal is not None:
                ext.journal.close()
                ext.state.retire()

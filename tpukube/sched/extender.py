"""Scheduler extender (L5) — HTTP webhooks for kube-scheduler.

SURVEY.md §2 C9 and §4.2: the reference runs an HTTP server implementing
the kube-scheduler extender protocol — /filter (feasibility via the group
allocator), /prioritize (NVLink/PCIe topology score), /bind (commit +
annotate). This is the TPU rendering: feasibility is free-share accounting
per node, the score is ICI-mesh locality (how snugly the pod's chips pack
against existing allocations — BASELINE's "ICI-mesh locality" replacing
NVLink scoring), and bind plans concrete chips with slicefit and records
the commitment in the ClusterState ledger + a pod ``alloc`` annotation.

The extender is a pure function of (pod, node annotations, ledger): no
apiserver connection exists here. The sim harness plays kube-scheduler
over real HTTP (aiohttp), which is exactly how the reference is tested
(SURVEY.md §5: "the extender is a pure function of (pods, node
annotations), so 'a cluster' is just data").
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from typing import Any, Optional

import numpy as np
from aiohttp import web

from tpukube.core import codec
from tpukube.core.config import TpuKubeConfig
from tpukube.core.types import (
    DEFAULT_SLICE,
    RESOURCE_TPU,
    RESOURCE_VTPU,
    AllocResult,
    PodInfo,
    TopologyCoord,
    make_device_id,
)
from tpukube.obs.registry import Histogram
from tpukube.sched import kube, policy, slicefit
from tpukube.sched.gang import (
    GangError,
    GangManager,
    GangReservation,
    NoSliceError,
)
from tpukube.sched.state import ClusterState, NodeView, StateError
from tpukube.trace import DecisionTrace

log = logging.getLogger("tpukube.extender")

MAX_SCORE = 10  # kube extender HostPriority scores are 0..10


class ExtenderError(RuntimeError):
    pass


class Extender:
    """Webhook logic, HTTP-free (the aiohttp app wraps this)."""

    # in-flight pods older than this are pruned (abandoned/deleted while
    # Pending); the scheduler re-filters before any bind anyway
    PENDING_TTL_S = 600.0
    LATENCY_WINDOW = 4096

    def __init__(
        self,
        config: TpuKubeConfig,
        state: Optional[ClusterState] = None,
        trace: Optional["DecisionTrace"] = None,
        clock=None,
        eviction_sink: Optional[deque] = None,
    ):
        from tpukube.core.clock import SYSTEM

        self._config = config
        # scheduling-semantic time (pending-webhook TTL, gang
        # reservation TTLs via the gang manager, assumed-plan expiry):
        # injectable so the discrete-event sim can compress hours of
        # churn into seconds; latency MEASUREMENT stays on real time
        self.clock = clock if clock is not None else SYSTEM
        self.state = state or ClusterState()
        # decision trace (SURVEY.md §6 tracing): make_app records at the
        # HTTP boundary, release() records inline; trace_capacity=0 disables
        if trace is None and config.trace_capacity > 0:
            trace = DecisionTrace(
                capacity=config.trace_capacity,
                path=config.trace_path or None,
                max_sink_bytes=config.trace_sink_max_bytes,
            )
        self.trace = trace
        # structured event journal (obs/events.py): the "why did that
        # happen" channel, fed by the gang manager and the preemption /
        # bind paths here, served on /statusz + /events and the
        # tpukube_events_total counter. capacity 0 disables.
        from tpukube.obs.events import EventJournal

        self.events = EventJournal(
            capacity=config.events_capacity,
            path=config.events_path or None,
            max_sink_bytes=config.events_sink_max_bytes,
        )
        # Decision provenance (obs/decisions.py, ISSUE 12): a bounded,
        # sampled, lock-free-on-record ring of per-pod stage events —
        # the "why did this pod land there / stay Pending / get
        # refused" chain — served on /explain, /statusz "decisions",
        # and `tpukube-obs explain`. None (the config default) builds
        # nothing: no stage is constructed, no series renders, and
        # every placement path is untouched.
        self.decisions = None
        # cycle phase profiling rides the same flag: queue / pin /
        # plan / answer / commit wall per cycle, plus the webhook-
        # answer-materialization timer that attributes the O(nodes)
        # filter-response cost. None = no observation anywhere.
        self.phase_hist = None
        if config.decisions_enabled:
            from tpukube.obs.decisions import DecisionLog

            self.decisions = DecisionLog(
                capacity=config.decisions_capacity,
                sample_rate=config.decisions_sample_rate,
                seed=config.decisions_seed,
                path=config.decisions_path or None,
                max_sink_bytes=config.decisions_sink_max_bytes,
            )
            self.phase_hist = Histogram(
                "tpukube_cycle_phase_seconds",
                buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                         0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0),
                help_text="Wall time per scheduling phase: queue wait, "
                          "snapshot pin, batch plan, webhook-answer "
                          "materialization, bind commit.")
        # Cluster-wide eviction bus: pods whose chips were taken back
        # (gang rollback/dissolve, preemption) and must be deleted by the
        # pod-lifecycle owner (sim harness / apiserver writer).
        # ``eviction_sink`` lets the sharded router (sched/shard.py)
        # hand all replicas ONE shared bus so a single EvictionExecutor
        # drains every replica's victims.
        self.pending_evictions: deque[str] = (
            eviction_sink if eviction_sink is not None else deque()
        )
        self.gang = GangManager(
            self.state,
            ttl_seconds=config.reservation_ttl_seconds,
            eviction_sink=self.pending_evictions,
            events=self.events,
            clock=self.clock,
        )
        # The epoch-cached scheduling snapshot (sched/snapshot.py),
        # owned by the gang manager and shared here: every filter/
        # prioritize/preemption cycle takes it once at the top (under
        # the decision lock) instead of re-deriving occupancy grids and
        # sweep tables from the ledger per webhook; the /metrics and
        # /statusz fragmentation renders read the same cache.
        self.snapshots = self.gang.snapshots
        # audit sentinel: on this fraction of scheduling cache hits the
        # cache rebuilds from the ledger and raises on divergence — the
        # runtime check behind the epoch-discipline lint (0 = off)
        self.snapshots.audit_rate = config.snapshot_audit_rate
        # incremental snapshot maintenance (ISSUE 10): epoch bumps
        # record SnapshotDeltas and the cache advances O(Δ); off =
        # rebuild-every-epoch (the parity oracle)
        self.snapshots.delta_enabled = config.snapshot_delta_enabled
        # bulk cold-start ingestion (ISSUE 15): handle("upsert_nodes")
        # routes through ClusterState.ingest_nodes — probe-validated
        # lazy ingest, one deferred epoch/delta/journal seam per batch.
        # Off = the same decision surface loops per-item upserts (the
        # parity oracle), and the tpukube_ingest_* series do not render.
        self.bulk_ingest = config.bulk_ingest_enabled
        # generation-based incremental resync (ISSUE 15): size the
        # ledger's alloc change log so lifecycle resyncs read O(Δ)
        # via allocs_since instead of the full ledger per wave
        # (capacity 0 keeps the legacy full read and the exposition
        # free of the tpukube_resync_* series)
        self.state.set_generation_log(config.generation_log_capacity)
        self.resync_incremental = config.generation_log_capacity > 0
        # Durable control-plane state (sched/journal.py, ISSUE 11):
        # with journal_enabled every ledger/gang mutation seam appends
        # one WAL record (enqueue-only — the journal's drain thread
        # owns the disk) and handle() captures a periodic checkpoint,
        # so a restarted daemon recovers O(Δ-since-checkpoint) via
        # journal.recover_extender instead of the O(fleet) cold
        # rebuild. None (the config default) journals nothing and
        # keeps behavior byte-identical.
        self.journal = None
        # per-payload node-line + per-alloc memo for checkpoint
        # captures (steady state costs O(Δ), not O(fleet))
        self._ckpt_cache: dict = {}
        if config.journal_enabled:
            from tpukube.sched.journal import StateJournal

            self.journal = StateJournal(
                config.journal_path,
                max_bytes=config.journal_max_bytes,
                fsync=config.journal_fsync,
                checkpoint_interval=config.checkpoint_interval_seconds,
                events=self.events,
                clock=self.clock,
            )
            self.state.set_journal(self.journal)
            self.gang.set_journal(self.journal)
        # Batched scheduling cycles (sched/cycle.py): with batch_enabled
        # the webhooks answer from a per-cycle batch plan instead of
        # re-planning per request; None (the config default) keeps the
        # legacy per-pod path bit-identically — nothing batch-related
        # is constructed or consulted.
        self.cycle = None
        if config.batch_enabled:
            from tpukube.sched.cycle import SchedulingCycle

            self.cycle = SchedulingCycle(self, config)
        # Pods seen at filter time, so /bind (which only carries names) can
        # recover the request: key -> (pod, uid, seen_monotonic).
        self._pending: dict[str, tuple[PodInfo, str, float]] = {}
        self._pending_lock = threading.Lock()
        self._pending_pruned = self.clock.monotonic()
        # Serializes every decision (mutation + trace record as ONE step):
        # webhooks run on the aiohttp loop but releases arrive from other
        # threads (sim pod-lifecycle, watchers); without this lock a trace
        # captured under concurrent load can interleave recording against
        # application order and replay divergent. RLock: bind() may release
        # inside a decision (gang undo path).
        self._decision_lock = threading.RLock()
        # latency capture for the north-star p50 (SURVEY.md §6 tracing);
        # bounded windows, not unbounded lists — this is a daemon
        self.latencies: dict[str, deque[float]] = {
            "filter": deque(maxlen=self.LATENCY_WINDOW),
            "prioritize": deque(maxlen=self.LATENCY_WINDOW),
            "bind": deque(maxlen=self.LATENCY_WINDOW),
        }
        # the same latencies as monotonic histogram buckets (counters,
        # cumulative since start — the windowed deques feed only the
        # quantile summaries); children pre-created so every handler's
        # _bucket series renders from the first scrape
        self.webhook_hist = Histogram("tpukube_webhook_latency_seconds",
                                      bucket_only=True)
        for handler in self.latencies:
            self.webhook_hist.labels(handler=handler)
        # True only while the batch planner's plan-time internal calls
        # run (under the decision lock): their filter/prioritize/bind
        # invocations are not webhooks and must not feed the histograms
        self._suppress_latency = False
        # Multi-tenant serving plane (tpukube/tenancy, ISSUE 9): with
        # tenancy_enabled the plane gates admissions (quotas + SLO-burn
        # shedding), orders the batch queue by dominant-resource
        # fairness, and biases preemption victim choice toward
        # over-share tenants. None (the config default) constructs
        # nothing — every placement path and the /metrics exposition
        # stay byte-identical to the pre-tenancy behavior.
        self.tenants = None
        if config.tenancy_enabled:
            from tpukube.tenancy import TenantPlane

            self.tenants = TenantPlane(
                config, self.state, self.gang, events=self.events,
                clock=self.clock,
            )
            # SLO-aware admission reads the DEFAULT_SLOS burn straight
            # off the daemon's own cumulative histograms — the same
            # objectives deploy/prometheus-rules.yaml alerts on
            self.tenants.burn.attach_default_slos({
                "gang_schedule_latency_seconds": self.gang.commit_hist,
                "tpukube_webhook_latency_seconds": self.webhook_hist,
            })
            # gang reservations carry their tenant so reserved-but-
            # unbound chips are charged to the right owner
            self.gang.tenant_of = self.tenants.tenant_of
            # tenancy refusals (quota denial / SLO shed) record their
            # verdict — shares and tenant-local burn at decision time
            # — into the provenance ring (None = no recording)
            self.tenants.decisions = self.decisions
        # Capacity analytics & demand forensics (obs/capacity.py,
        # ISSUE 17): flight-recorder ring + stranded-demand forensics +
        # what-if probes. None (the config default) constructs nothing
        # — no sample is ever taken, no series renders, /capacity 404s,
        # and placements/exposition stay byte-identical. Built AFTER
        # snapshots/cycle/tenants so a sample can read all of them.
        self.capacity = None
        if config.capacity_enabled:
            from tpukube.obs.capacity import CapacityRecorder

            self.capacity = CapacityRecorder(self, config)
        # Fleet elasticity (ISSUE 19): the graceful drain/decommission
        # choreography (sched/drain.py) and the autoscaler loop
        # (sched/autoscale.py). None (the config defaults) constructs
        # nothing — no cordon state is consulted on any placement
        # path, no tpukube_drain_* / tpukube_autoscaler_* series
        # render, and /statusz carries no drain/autoscaler section.
        # Built AFTER snapshots/cycle/tenants/capacity so a tick can
        # read all of them (queue depth, SLO burn, utilization).
        self.drain = None
        if config.drain_enabled:
            from tpukube.sched.drain import DrainCoordinator

            self.drain = DrainCoordinator(self, config)
        self.autoscaler = None
        if config.autoscale_enabled:
            from tpukube.sched.autoscale import Autoscaler

            self.autoscaler = Autoscaler(self, config)
        self.preemptions = 0   # victims evicted for higher-priority gangs
        self.binds_total = 0   # successful binds (metrics counter)
        # The bind EFFECTOR: with bindVerb configured, kube-scheduler
        # delegates the binding itself to the extender — returning success
        # without creating the Binding object leaves the pod Pending
        # forever on a real cluster. cli wires apiserver.pod_binder(api)
        # here; None in sim (the harness plays the apiserver and applies
        # the response's annotations itself). The call runs OUTSIDE the
        # decision lock (_handle_bind) so apiserver latency never stalls
        # filter/prioritize for the whole cluster.
        self.binder = None
        # The PDB PRECHECK: a callable pod_key -> Optional[bool] (True =
        # evictable now, False = a PodDisruptionBudget blocks it, None =
        # cannot determine). cli wires a dry-run Eviction POST here.
        # Consulted by _handle_bind BEFORE a gang's first bind executes
        # its preemption plan: evictions are irreversible, so a plan with
        # a PDB-blocked victim is refused loudly instead of half-executed
        # (the reservation then TTLs out without costing anyone chips).
        # Runs OUTSIDE the decision lock and is NOT part of the recorded
        # decision — a refused precheck leaves no state to replay.
        self.evict_precheck = None
        # pod_key -> (reservation, this-bind-committed-the-gang), written
        # by bind() when a binder is set, consumed by _handle_bind's
        # effector undo
        self._bind_gang_info: dict[str, tuple[Any, bool]] = {}
        # Degraded mode (ISSUE 4): a callable returning a human reason
        # while the apiserver circuit is open (None = healthy). While
        # degraded, /filter and /bind FAIL SAFE — no feasibility
        # answer, no preemption plan, no bind — because an extender
        # that cannot reach the apiserver cannot effect (or verify) any
        # decision it makes; the scheduler retries once the circuit
        # half-opens. cli wires this to the channel's CircuitBreaker;
        # None (the default) disables the gate entirely. The gate must
        # only read memory — it is consulted on the webhook hot path.
        self.degraded_gate = None
        # the apiserver channel's Retrier/CircuitBreaker, attached by
        # the daemon main purely so /metrics can export their counters
        # (tpukube_retry_* / tpukube_circuit_*); None in sim/dev
        self.api_retrier = None
        self.api_circuit = None

    def _emit_event(self, reason: str, obj: str, message: str,
                    warning: bool = True) -> None:
        """Journal an event; never let observability fail a webhook."""
        try:
            self.events.emit(
                reason, obj=obj, message=message,
                type="Warning" if warning else "Normal",
            )
        except Exception:
            log.exception("event emit failed: %s %s", reason, obj)

    def _note_decision(self, pod_key: str, stage: str, **fields) -> None:
        """Guarded provenance record — the one place the sampling gate
        lives for the extender's cold refusal/lifecycle seams (hot
        paths gate explicitly so unsampled pods never build kwargs).
        The decision-provenance lint accepts this helper as a
        recording delegate, like the tenancy plane's _refuse."""
        dlog = self.decisions
        if dlog is not None and dlog.wants(pod_key):
            dlog.record(pod_key, stage, **fields)

    def _degraded_reason(self) -> Optional[str]:
        """The degraded gate's answer, never letting a broken gate
        break scheduling (a gate failure reads as healthy)."""
        gate = self.degraded_gate
        if gate is None:
            return None
        try:
            return gate()
        except Exception:
            log.exception("degraded gate failed; treating as healthy")
            return None

    def _remember(self, pod: PodInfo) -> None:
        now = self.clock.monotonic()
        with self._pending_lock:
            self._pending[pod.key()] = (pod, pod.uid, now)
            # amortized prune: a full scan per call was O(pending) on
            # the batch fast path (100k-pod kilonode traces); sweeping
            # a few times per TTL window keeps the same bound
            if now - self._pending_pruned < self.PENDING_TTL_S / 4:
                return
            self._pending_pruned = now
            stale = [
                k for k, (_, _, t) in self._pending.items()
                if now - t > self.PENDING_TTL_S
            ]
            for k in stale:
                del self._pending[k]

    # -- request decoding --------------------------------------------------
    @staticmethod
    def device_request(pod: PodInfo) -> Optional[tuple[str, int]]:
        """(resource, count) for the pod's TPU ask, or None for non-TPU pods.
        A pod asking for both resources is malformed (different node modes)."""
        req = pod.requests()
        tpu = req.get(RESOURCE_TPU, 0)
        vtpu = req.get(RESOURCE_VTPU, 0)
        if tpu and vtpu:
            raise ExtenderError(
                f"{pod.key()}: requests both {RESOURCE_TPU} and {RESOURCE_VTPU}"
            )
        if tpu:
            return RESOURCE_TPU, tpu
        if vtpu:
            return RESOURCE_VTPU, vtpu
        return None

    def _ingest_nodes(self, raw_nodes: list[dict[str, Any]]) -> list[str]:
        names = []
        if self.bulk_ingest:
            # the webhook body re-sends the whole candidate fleet every
            # request: ride the batch fast path (ONE lock hold, known
            # unchanged payloads answered by signature, new nodes
            # staged lazily). A bad payload still aborts the request
            # like the per-node path's raise did.
            items = []
            for obj in raw_nodes:
                name, annotations = kube.node_name_and_annotations(obj)
                items.append({"name": name, "annotations": annotations})
                names.append(name)
            for res in self.state.ingest_nodes(items):
                if isinstance(res, dict) and res.get("error"):
                    raise StateError(res["error"])
            self.state.maybe_start_warmer()
            return names
        for obj in raw_nodes:
            name, annotations = kube.node_name_and_annotations(obj)
            self.state.upsert_node(name, annotations)
            names.append(name)
        return names

    # -- /filter -----------------------------------------------------------
    def filter(
        self,
        pod: PodInfo,
        raw_nodes: Optional[list[dict[str, Any]]] = None,
        node_names: Optional[list[str]] = None,
    ) -> tuple[list[Any], dict[str, str]]:
        """Feasibility webhook. Two request modes, matching the upstream
        protocol: full node objects (ingested into the state cache), or
        nodeCacheCapable ``node_names`` answered purely from the cache.
        The feasible list holds objects or names respectively."""
        t0 = time.monotonic()
        try:
            if raw_nodes is not None:
                names = self._ingest_nodes(raw_nodes)
            else:
                names = list(node_names or [])
            ask = self.device_request(pod)
            if ask is None:
                # not a TPU pod: everything is feasible, nothing to track
                return (raw_nodes if raw_nodes is not None else names), {}
            by_name = (dict(zip(names, raw_nodes))
                       if raw_nodes is not None else None)
            resource, count = ask
            if self.tenants is not None:
                # tenancy admission gate: quota breaches and SLO-burn
                # sheds refuse BEFORE any reservation or preemption
                # plan exists — the refusal (journaled by the plane)
                # rides back as the filter error and the scheduler's
                # requeue turns it into a deferral
                refusal = self.tenants.admit(pod, resource, count)
                if refusal is not None:
                    raise ExtenderError(refusal)
            self._remember(pod)
            dlog = self.decisions
            wants = dlog is not None and dlog.wants(pod.key())
            res: Optional[GangReservation] = None
            if pod.group is not None:
                if resource != RESOURCE_TPU:
                    raise ExtenderError(
                        f"{pod.key()}: gang scheduling requires whole-chip "
                        f"({RESOURCE_TPU}) requests"
                    )
                try:
                    res = self.gang.ensure_reservation(pod, count)
                except NoSliceError:
                    # no contiguous slice — a high-priority gang may evict
                    # cheaper pods to open one (SURVEY.md C11, config 5).
                    # Other GangErrors are configuration mistakes and must
                    # never cost innocent pods their chips.
                    res = self._try_preemption(pod, count)
                if not self.gang.assignable(res, count):
                    # replica beyond min_member of a full gang: schedule it
                    # as a normal pod rather than wedging it Pending forever
                    res = None
                if res is not None and self.trace is not None:
                    # timeline span: this member attached to (or created)
                    # the gang's slice reservation in this filter cycle
                    self.trace.span(
                        "gang_reserve", pod.key(),
                        gang=f"{pod.namespace}/{pod.group.name}",
                        chips=res.total_chips(), committed=res.committed,
                    )
                if res is not None and wants:
                    dlog.record(
                        pod.key(), "gang_reserve",
                        gang=f"{pod.namespace}/{pod.group.name}",
                        chips=res.total_chips(),
                        committed=res.committed,
                    )
            else:
                self.gang.sweep()
            reserved = self._reserved_by_slice() if res is None else None
            # one availability pass per webhook, not one coord scan per
            # node (hot: 64-member gang x 32 nodes x 64 reserved coords)
            gang_counts = (self.gang.node_availability(res)
                           if res is not None else None)
            # the webhook-answer materialization — the O(nodes) loop
            # that builds the wire lists. At 10k nodes THIS is the
            # filter p99, and the phase timer finally attributes it
            # (suppressed for plan-time internal calls, which answer
            # no webhook).
            at0 = (time.perf_counter()
                   if self.phase_hist is not None
                   and not self._suppress_latency else None)
            feasible, failed = [], {}
            for name in names:
                if res is not None:
                    reason = self.gang.feasibility_from(
                        gang_counts, res, name
                    )
                else:
                    reason = self._node_feasibility(name, resource, count, reserved)
                if reason is None:
                    feasible.append(by_name[name] if by_name is not None
                                    else name)
                else:
                    failed[name] = reason
            if at0 is not None:
                self.phase_hist.labels(phase="answer").observe(
                    time.perf_counter() - at0
                )
            if wants:
                # per-stage candidate pruning: which reason rejected
                # how many nodes — the why-pending data
                pruned: dict[str, int] = {}
                for r in failed.values():
                    pruned[r] = pruned.get(r, 0) + 1
                dlog.record(
                    pod.key(), "filter",
                    candidates=len(names), feasible=len(feasible),
                    pruned=pruned,
                )
            return feasible, failed
        finally:
            self._observe_latency("filter", time.monotonic() - t0)

    def _observe_latency(self, handler: str, seconds: float) -> None:
        """One webhook latency sample: into the bounded window (quantile
        summaries) AND the cumulative histogram (_bucket counters).
        Suppressed while the batch planner runs its plan-time internal
        calls (SchedulingCycle._quiet) so each real webhook records
        exactly one sample in batch mode too."""
        if self._suppress_latency:
            return
        self.latencies[handler].append(seconds)
        self.webhook_hist.labels(handler=handler).observe(seconds)

    def _reserved_by_slice(self) -> dict[str, frozenset[TopologyCoord]]:
        return self.snapshots.current().reserved_by_slice()

    def _try_preemption(self, pod: PodInfo, count: int) -> GangReservation:
        """Open a contiguous slice for a gang by planning the eviction of
        lower-priority pods. Plans per ICI slice (victim chips only help
        inside their own slice) and reserves the cheapest plan across
        slices — TWO-PHASE: victims are recorded on the reservation, not
        evicted; the evictions execute at the gang's first bind
        (_execute_pending_preemption). A gang that filters but never binds
        (crash, queue churn) costs no innocent pod its chips — the TTL
        sweep drops the reservation and the victims were never touched.
        Raises GangError (propagates unschedulability) if no eligible
        victim set exists or the pod has no priority to preempt with."""
        assert pod.group is not None
        # one snapshot for the whole preemption plan: the planner's
        # blocked sets (unhealthy + terminating) and link state come from
        # the same epoch the candidate sweep is built against
        snap = self.snapshots.current()
        slice_ids = snap.slice_ids()
        if not slice_ids or pod.priority <= 0:
            raise GangError(
                f"gang {pod.namespace}/{pod.group.name}: no contiguous slice "
                f"and priority {pod.priority} cannot preempt"
            )
        total = pod.group.min_member * count
        if pod.group.shape is not None:
            sx, sy, sz = pod.group.shape
            if sx * sy * sz != total:
                raise GangError(
                    f"gang {pod.namespace}/{pod.group.name}: shape "
                    f"{pod.group.shape} holds {sx * sy * sz} chips but the "
                    f"gang needs {total} — refusing to preempt for it"
                )
        workloads = self._preemption_workloads()
        # tenant-aware victim bias (tpukube/tenancy): at equal priority
        # cost the planner prefers boxes whose victims belong to the
        # most over-entitlement tenants; None with tenancy off leaves
        # the legacy ranking bit-identical
        overshare = (self.tenants.overshare_map()
                     if self.tenants is not None else None)
        plan = None
        plan_slice = None
        best_rank = None
        for sid in slice_ids:
            # blocked = unhealthy chips PLUS terminating victims' chips:
            # the latter are ledger-free but physically held, and no
            # eviction can free them sooner — a plan over them would
            # reserve with zero victims and bind ungated onto chips a
            # dying container still owns (ADVICE round 5 medium)
            ss = snap.slice(sid)
            cand = policy.find_preemption_plan(
                [w for w in workloads if w.slice_id == sid],
                ss.mesh,
                ss.unhealthy | ss.terminating,
                total,
                pod.group.shape,
                pod.priority,
                broken=ss.broken,
                overshare=overshare,
            )
            if cand is None:
                continue
            rank = (cand.cost_priority_sum, cand.victim_count, sid)
            if best_rank is None or rank < best_rank:
                best_rank, plan, plan_slice = rank, cand, sid
        if plan is None:
            if pod.group.allow_dcn and pod.group.shape is None:
                split = self._plan_split_preemption(
                    workloads, total, count, pod.priority,
                    overshare=overshare,
                )
                if split is not None:
                    victims = [w for p in split.values() for w in p.victims]
                    log.warning(
                        "gang %s/%s plans to preempt %d workload(s) for a "
                        "DCN-split %d-chip reservation over %s (deferred "
                        "to first bind)",
                        pod.namespace, pod.group.name, len(victims), total,
                        sorted(split),
                    )
                    if self.trace is not None:
                        self.trace.span(
                            "preemption_plan", pod.key(),
                            gang=f"{pod.namespace}/{pod.group.name}",
                            victims=len(victims), slices=sorted(split),
                        )
                    self._note_decision(
                        pod.key(), "preemption_plan",
                        gang=f"{pod.namespace}/{pod.group.name}",
                        victims=len(victims), slices=sorted(split),
                        overshare_bias=sorted(overshare or {}),
                    )
                    self._emit_event(
                        "PreemptionPlanned",
                        f"gang/{pod.namespace}/{pod.group.name}",
                        f"{len(victims)} victim workload(s) planned for a "
                        f"DCN-split {total}-chip reservation "
                        f"(deferred to first bind)",
                    )
                    return self.gang.reserve_exact_split(
                        pod, count,
                        {sid: p.coords for sid, p in split.items()},
                        pending_victims=victims,
                    )
            raise GangError(
                f"gang {pod.namespace}/{pod.group.name}: no victim set opens "
                f"a contiguous {total}-chip slice at priority {pod.priority} "
                f"in any of {len(slice_ids)} ICI slices"
            )
        log.warning(
            "gang %s/%s plans to preempt %d workloads (priority sum %d) "
            "for a %d-chip slice in %s (deferred to first bind)",
            pod.namespace, pod.group.name,
            plan.victim_count, plan.cost_priority_sum, total, plan_slice,
        )
        if self.trace is not None:
            self.trace.span(
                "preemption_plan", pod.key(),
                gang=f"{pod.namespace}/{pod.group.name}",
                victims=plan.victim_count,
                cost_priority_sum=plan.cost_priority_sum,
                slices=[plan_slice],
            )
        self._note_decision(
            pod.key(), "preemption_plan",
            gang=f"{pod.namespace}/{pod.group.name}",
            victims=plan.victim_count,
            cost_priority_sum=plan.cost_priority_sum,
            slices=[plan_slice],
            overshare_bias=sorted(overshare or {}),
        )
        self._emit_event(
            "PreemptionPlanned",
            f"gang/{pod.namespace}/{pod.group.name}",
            f"{plan.victim_count} victim workload(s), priority sum "
            f"{plan.cost_priority_sum}, for a {total}-chip slice in "
            f"{plan_slice} (deferred to first bind)",
        )
        return self.gang.reserve_exact(
            pod, count, plan.coords, slice_id=plan_slice,
            pending_victims=plan.victims,
        )

    def _execute_pending_preemption(
        self, res: GangReservation, view: NodeView, device_ids: list[str]
    ) -> None:
        """Phase two of preemption, at the gang's first bind: the planned
        victims actually lose their chips. Runs under the decision lock
        (handle()), so exactly one member executes the plan.

        Evictions are irreversible, so they run only after this member's
        commit is certain to succeed: every minted id must be on a healthy
        chip and held by nobody — or by a declared victim about to be
        evicted. A failed pre-check raises WITHOUT touching the victims
        (the reservation stays pending; a sick slice is the sweep's job).

        Execution does NOT let this bind proceed: a 2xx Eviction only
        starts graceful termination, and on a single-owner TPU runtime a
        gang pod started while its victim's containers still hold the
        chips crash-loops for the whole grace period. The victims are
        registered as terminating (gating every member bind + masking
        their chips) and this bind fails retryably; binds resume once the
        eviction executor / lifecycle watch confirms the pod objects gone
        (the recorded ``victim_gone`` decision). kube-scheduler's own
        preemption waits for victim deletion the same way."""
        from tpukube.core.types import Health, parse_device_id

        victims = self.gang.peek_pending_victims(res)
        if not victims:
            return
        victim_pods = self._victim_pod_keys(victims)
        holders = {
            did: a.pod_key
            for a in self.state.allocations()
            if a.node_name == view.info.name
            for did in a.device_ids
        }
        for did in device_ids:
            index, _ = parse_device_id(did)
            if view.chip(index).health is not Health.HEALTHY:
                raise ExtenderError(
                    f"{did}: chip unhealthy; preemption not executed "
                    "(reservation will be swept)"
                )
            holder = holders.get(did)
            if holder is not None and holder not in victim_pods:
                raise ExtenderError(
                    f"{did}: held by non-victim {holder}; preemption not "
                    "executed, scheduler will re-run the cycle"
                )
        victims = self.gang.take_pending_victims(res)
        evicted_pods, held = self._apply_victims(victims)
        self.preemptions += evicted_pods
        log.warning(
            "gang %s/%s executes deferred preemption at first bind: "
            "%d workload(s) / %d pod(s) evicted",
            res.namespace, res.group.name, len(victims), evicted_pods,
        )
        self._emit_event(
            "PreemptionExecuted",
            f"gang/{res.namespace}/{res.group.name}",
            f"{len(victims)} workload(s) / {evicted_pods} pod(s) evicted "
            f"at the gang's first bind",
        )
        if held:
            self.gang.register_terminating(res, held)
            raise ExtenderError(
                f"gang {res.namespace}/{res.group.name}: preemption "
                f"executed; waiting for {len(held)} victim pod(s) to "
                "finish terminating — scheduler will re-run the cycle"
            )

    def _victim_pod_keys(self, victims) -> set[str]:
        """Every pod a victim-workload list would evict: the workloads'
        own pods plus, for gang victims, their reservations' assigned
        members. One definition shared by the PDB precheck and the
        execution pre-validation — they must never test different sets."""
        victim_pods: set[str] = set()
        for w in victims:
            victim_pods.update(w.pod_keys)
            if w.gang_key is not None:
                vres = self.gang.reservation(*w.gang_key)
                if vres is not None:
                    victim_pods.update(vres.assigned)
        return victim_pods

    def _apply_victims(self, victims) -> tuple[int, dict]:
        """Evict a victim set: gangs dissolve wholesale (once, even when a
        DCN-spanning gang appears as several per-slice workloads), plain
        pods release + queue for eviction. Victims that vanished between
        plan and execution (released naturally) are skipped. Returns
        (pods evicted, evicted pod -> (slice, coords still physically
        held) — the termination gate's input)."""
        held: dict[str, tuple[str, list[TopologyCoord]]] = {}

        def note_held(pk: str) -> None:
            alloc = self.state.allocation(pk)
            if alloc is None:
                return
            sid = self.state.slice_of_node(alloc.node_name)
            if sid is not None:
                held[pk] = (sid, [TopologyCoord.of(c) for c in alloc.coords])

        evicted_pods = 0
        dissolved: set[tuple[str, str]] = set()

        def note_preempted(pk: str) -> None:
            # provenance: the victim's own chain must answer "where
            # did my chips go" — not just the preemptor's
            self._note_decision(pk, "preempted")

        for victim in victims:
            if victim.gang_key is not None:
                if victim.gang_key in dissolved:
                    continue
                dissolved.add(victim.gang_key)
                vres = self.gang.reservation(*victim.gang_key)
                if vres is not None:
                    for pk in list(vres.assigned):
                        note_held(pk)
                gone = self.gang.dissolve(victim.gang_key)
                evicted_pods += len(gone)
                for pk in gone:
                    note_preempted(pk)
            else:
                for pk in victim.pod_keys:
                    note_held(pk)
                    if self.state.release(pk) is not None:
                        self.pending_evictions.append(pk)
                        evicted_pods += 1
                        note_preempted(pk)
                        self._emit_event(
                            "VictimEvicted", f"pod/{pk}",
                            "released and queued for eviction "
                            "(preempted by a higher-priority gang)",
                        )
                    else:
                        held.pop(pk, None)  # vanished between plan and now
        return evicted_pods, held

    def _plan_split_preemption(
        self, workloads: list[policy.Workload], total: int,
        chips_per_pod: int, priority: int,
        overshare: Optional[dict[str, float]] = None,
    ) -> Optional[dict[str, policy.PreemptionPlan]]:
        """Preemption for a DCN-split gang: one cost-optimal box per slice
        (greedy over slices by free capacity, largest feasible volume
        first — the preemption mirror of GangManager._plan_dcn_split).
        Returns slice -> plan covering exactly ``total`` chips, or None."""
        snap = self.snapshots.current()
        order = sorted(
            snap.slice_ids(),
            key=lambda s: (snap.slice(s).utilization, s),
        )
        parts: dict[str, policy.PreemptionPlan] = {}
        remaining = total
        for sid in order:
            if remaining == 0:
                break
            ss = snap.slice(sid)
            mesh = ss.mesh
            in_slice = [w for w in workloads if w.slice_id == sid]
            # same blocked-set rule as the single-slice path: chips a
            # terminating victim still physically holds are unopenable
            unhealthy = ss.unhealthy | ss.terminating
            broken = ss.broken
            max_vol = min(
                remaining,
                ((mesh.num_chips - len(unhealthy)) // chips_per_pod)
                * chips_per_pod,
            )
            vol = max_vol
            while vol >= chips_per_pod:
                cand = policy.find_preemption_plan(
                    in_slice, mesh, unhealthy, vol, None, priority,
                    broken=broken, overshare=overshare,
                )
                if cand is not None:
                    parts[sid] = cand
                    remaining -= vol
                    break
                vol -= chips_per_pod
        return parts if remaining == 0 else None

    def _preemption_workloads(self) -> list[policy.Workload]:
        """Current workloads at preemption granularity: whole gangs (with
        their reserved-but-unassigned chips) and free-standing pods."""
        out: list[policy.Workload] = []
        gang_pods: set[str] = set()
        for res in self.gang.snapshot():
            members = sorted(res.assigned)
            gang_pods.update(members)
            prios = [self.state.priority_of(k) for k in members]
            # Blocking priority covers members NOT yet bound: the
            # reservation records its gang's priority, so a freshly
            # reserving prio-100 gang is never the cheap victim of a
            # prio-1 preemptor (priority inversion). Cost likewise counts
            # unarrived members at the reservation's priority.
            unarrived = max(0, res.group.min_member - len(members))
            priority = max([res.priority, *prios])
            cost = sum(prios) + res.priority * unarrived
            # one Workload per slice the gang touches (the planner works
            # slice-by-slice); evicting ANY part dissolves the whole gang,
            # so each part carries the gang's full eviction cost
            for sid, coords in res.slice_coords.items():
                chips = set(coords)
                for k in members:
                    entry = res.assigned.get(k)  # may race with on_release
                    if entry is not None and entry[0] == sid:
                        chips.update(entry[1])
                out.append(policy.Workload(
                    id=f"gang:{res.namespace}/{res.group.name}@{sid}",
                    priority=priority,
                    cost=cost,
                    coords=frozenset(chips),
                    pod_keys=tuple(members),
                    gang_key=res.key,
                    slice_id=sid,
                    tenant=(res.tenant or self.tenants.default
                            if self.tenants is not None else ""),
                ))
        for alloc in self.state.allocations():
            if alloc.pod_key in gang_pods:
                continue
            sid = self.state.slice_of_node(alloc.node_name)
            if sid is None:
                # node view gone (deleted mid-teardown): its chips are not
                # in any slice's occupied set either, so skipping keeps the
                # planner's view consistent — guessing a slice would plant
                # these coords in the wrong coordinate space
                continue
            prio = self.state.priority_of(alloc.pod_key)
            out.append(policy.Workload(
                id=alloc.pod_key,
                priority=prio,
                cost=prio,
                coords=frozenset(TopologyCoord.of(c) for c in alloc.coords),
                pod_keys=(alloc.pod_key,),
                slice_id=sid,
                tenant=(self.tenants.tenant_of_alloc(alloc)
                        if self.tenants is not None else ""),
            ))
        return out

    def _node_feasibility(
        self,
        name: str,
        resource: str,
        count: int,
        reserved: Optional[dict[str, set[TopologyCoord]]] = None,
    ) -> Optional[str]:
        """None if feasible, else a human-readable reason. ``reserved`` is
        the per-slice gang mask — pass it in when calling per-node in a
        loop (coords are slice-local, so the mask is keyed by slice)."""
        view = self.state.node(name)
        if view is None:
            return "no tpukube node-topology annotation"
        if self.drain is not None and self.state.is_cordoned(name):
            # draining (ISSUE 19): live allocs keep serving, new
            # placements are refused — capacity forensics root-causes
            # demand stranded this way as "draining", not "capacity"
            return "node cordoned (draining)"
        vtpu_node = view.shares_per_chip > 1
        if resource == RESOURCE_VTPU:
            if not vtpu_node:
                return "node is whole-chip mode, pod wants vTPU shares"
            free = view.total_free_shares()
            if free < count:
                return f"wants {count} vTPU shares, node has {free}"
            return None
        if vtpu_node:
            return "node is vTPU mode, pod wants whole chips"
        sid = view.info.slice_id
        mask = (
            reserved.get(sid, set()) if reserved is not None
            else self.gang.reserved_coords(sid)
        )
        free = sum(1 for c in view.free_chips() if c.coord not in mask)
        if free < count:
            return f"wants {count} chips, node has {free} free (gang reservations excluded)"
        return None

    # -- /prioritize -------------------------------------------------------
    def prioritize(
        self,
        pod: PodInfo,
        raw_nodes: Optional[list[dict[str, Any]]] = None,
        node_names: Optional[list[str]] = None,
    ) -> dict[str, int]:
        t0 = time.monotonic()
        try:
            if raw_nodes is not None:
                names = self._ingest_nodes(raw_nodes)
            else:
                names = list(node_names or [])
            try:
                ask = self.device_request(pod)
            except ExtenderError:
                return {n: 0 for n in names}
            if ask is None:
                return {n: 0 for n in names}
            resource, count = ask
            if pod.group is not None and resource == RESOURCE_TPU:
                res = self.gang.reservation(pod.namespace, pod.group.name)
                if res is not None and self.gang.assignable(res, count):
                    counts = self.gang.node_availability(res)
                    return self._record_scores(pod, {
                        n: self.gang.score_from(counts, n)
                        for n in names
                    })
                if res is None:
                    return {n: 0 for n in names}
                # overflow replica of a full gang: fall through to normal
            # the occupancy sweeps and gang masks depend only on cluster
            # state — read once per request from the epoch-cached
            # snapshot, which survives ACROSS requests until the next
            # ledger/reservation mutation (the per-webhook sweep rebuild
            # this replaces was the prioritize hot path); both are
            # slice-keyed (coords are slice-local)
            snap = self.snapshots.current()
            reserved = snap.reserved_by_slice()
            sweeps: Optional[dict[str, "slicefit._Sweep"]] = None
            if self._config.score_mode == "topology" and resource == RESOURCE_TPU:
                sweeps = {
                    sid: snap.slice(sid).blocked_sweep()
                    for sid in snap.slice_ids()
                }
            scores: dict[str, int] = {}
            for name in names:
                scores[name] = self._score_node(name, resource, count, sweeps, reserved)
            return self._record_scores(pod, scores)
        finally:
            self._observe_latency("prioritize", time.monotonic() - t0)

    def _record_scores(self, pod: PodInfo,
                       scores: dict[str, int]) -> dict[str, int]:
        """Provenance for the scoring decision: the top-k nodes and
        their scores (the why-here data — which candidates the pick
        actually beat). Pass-through when provenance is off or the pod
        is unsampled."""
        dlog = self.decisions
        if dlog is not None and scores and dlog.wants(pod.key()):
            top = sorted(scores.items(),
                         key=lambda kv: (-kv[1], kv[0]))[:5]
            dlog.record(pod.key(), "prioritize", nodes=len(scores),
                        top=[[n, s] for n, s in top])
        return scores

    def _score_node(
        self,
        name: str,
        resource: str,
        count: int,
        sweeps: Optional[dict[str, "slicefit._Sweep"]] = None,
        reserved: Optional[dict[str, set[TopologyCoord]]] = None,
    ) -> int:
        view = self.state.node(name)
        if view is None or self._node_feasibility(name, resource, count, reserved):
            return 0
        mode = self._config.score_mode
        n_chips = len(view.info.chips)
        if mode == "spread":
            free_frac = view.total_free_shares() / (
                n_chips * view.shares_per_chip or 1
            )
            return round(MAX_SCORE * free_frac)
        if mode == "binpack":
            used_frac = 1 - view.total_free_shares() / (
                n_chips * view.shares_per_chip or 1
            )
            return round(MAX_SCORE * used_frac)
        # "topology" (default): ICI-mesh locality.
        if resource == RESOURCE_TPU and count == 1 and sweeps is not None:
            # vectorized fast path for the commonest request: the node's
            # score is the snuggest single free chip it offers, read off
            # the per-request contact grid (bind re-plans the concrete
            # chip; scoring only needs the node's best)
            sid = view.info.slice_id
            sweep = sweeps.get(sid)
            if sweep is not None:
                mask_set = (
                    reserved.get(sid, set()) if reserved is not None else set()
                )
                cg = sweep.contact_grid()
                best = -1
                for chip in view.free_chips():
                    if chip.coord in mask_set:
                        continue
                    v = int(cg[chip.coord])
                    if v > best:
                        best = v
                return round(MAX_SCORE * best / 6) if best >= 0 else 0
        plan = self._plan_chips(view, resource, count, reserved)
        if plan is None:
            return 0
        if resource == RESOURCE_VTPU:
            # prefer riding already-used chips (keeps whole chips free)
            reused = sum(
                1
                for c in plan
                if view.used_share_count(self._index_at(view, c))
            )
            return min(MAX_SCORE, round(MAX_SCORE * (reused + 1) / (len(plan) + 1)))
        # whole chips: snugness — chips packed against walls/allocations
        # leave the mesh least fragmented, keeping future gangs' boxes open
        sid = view.info.slice_id
        sweep = sweeps.get(sid) if sweeps is not None else None
        if sweep is None:
            sweep = self.snapshots.current().slice(sid).occupancy_sweep()
        contact = 0
        max_contact = 0
        for coord in plan:
            contact += sweep.contact_point(coord)
            max_contact += 6
        return round(MAX_SCORE * contact / max_contact) if max_contact else 0

    @staticmethod
    def _index_at(view: NodeView, coord: TopologyCoord) -> int:
        try:
            return view.index_at(coord)  # O(1) via the view's coord map
        except StateError as e:
            raise ExtenderError(str(e)) from None

    # -- placement planning -------------------------------------------------
    def _plan_chips(
        self,
        view: NodeView,
        resource: str,
        count: int,
        reserved: Optional[dict[str, set[TopologyCoord]]] = None,
    ) -> Optional[list[TopologyCoord]]:
        """Choose concrete chips on one node for a request.

        Whole chips: slicefit over the global mesh, restricted to this
        node's free chips (everything else masked occupied) — irregular
        allowed, a host block is tightly connected anyway.
        vTPU: chip-level choice only (shares are fungible); fill
        partially-used chips first to keep whole chips free.
        """
        if resource == RESOURCE_VTPU:
            chips = sorted(
                (c for c in view.info.chips if view.free_shares(c) > 0),
                key=lambda c: (-view.used_share_count(c.index), c.index),
            )
            out: list[TopologyCoord] = []
            remaining = count
            for chip in chips:
                take = min(remaining, view.free_shares(chip))
                out.extend([chip.coord] * take)
                remaining -= take
                if remaining == 0:
                    return out
            return None
        sid = view.info.slice_id
        ss = self.snapshots.current().slice(sid)
        mesh = ss.mesh
        mask_set = (
            reserved.get(sid, set()) if reserved is not None
            else ss.reserved
        )
        node_free = {
            c.coord for c in view.free_chips() if c.coord not in mask_set
        }
        if len(node_free) < count:
            return None
        if count == 1:
            # fast path for the commonest request (1 chip/pod): pick the
            # node's free chip snuggest against GLOBAL occupancy — the
            # same quantity /prioritize's contact-grid scoring maximizes,
            # so the bound chip realizes the score the node won on (other
            # hosts' FREE chips are not blockers; treating them as such,
            # as the old mask form did, mis-ranked fragmentation)
            blocked = ss.occupied | mask_set | ss.absent
            best = max(
                node_free,
                key=lambda c: (
                    slicefit.point_contact(mesh, c, lambda nb: nb in blocked),
                    tuple(-v for v in c),
                ),
            )
            return [best]
        # everything outside this node's free set is masked occupied —
        # a NODE-LOCAL grid, so it cannot live in the cluster snapshot;
        # built directly as an ndarray and handed to the slicefit
        # wrapper (whose sweep build is the module's own seam)
        mask = np.ones(mesh.dims, dtype=bool)
        for c in node_free:
            mask[tuple(c)] = False
        placed = slicefit.find_slice(
            mesh, mask, count=count, allow_irregular=True,
            broken=ss.broken,
        )
        if placed is not None:
            return placed
        # Free chips exist but form no box/connected region (e.g. diagonal
        # survivors in a host block). Chips on ONE HOST are always mutually
        # usable — adjacency is a preference, not a requirement, for
        # non-gang pods — so fall back to any free chips, keeping the
        # filter's count-based feasibility and bind in agreement.
        chosen = sorted(node_free)[:count]
        return [TopologyCoord.of(c) for c in chosen]

    # -- /bind --------------------------------------------------------------
    def bind(self, pod_name: str, namespace: str, uid: str, node_name: str) -> AllocResult:
        t0 = time.monotonic()
        try:
            key = f"{namespace}/{pod_name}"
            with self._pending_lock:
                entry = self._pending.get(key)
            if entry is None:
                raise ExtenderError(
                    f"bind for {key} without a preceding filter (restart? "
                    "scheduler will re-run the cycle)"
                )
            pod, cached_uid, _ = entry
            if uid and cached_uid and uid != cached_uid:
                raise ExtenderError(
                    f"bind for {key}: uid {uid} does not match the filtered "
                    f"pod {cached_uid} (deleted and recreated?)"
                )
            ask = self.device_request(pod)
            if ask is None:
                raise ExtenderError(f"{key}: no TPU request to bind")
            resource, count = ask
            view = self.state.node(node_name)
            if view is None:
                raise ExtenderError(f"bind to unknown node {node_name}")
            res: Optional[GangReservation] = None
            if pod.group is not None and resource == RESOURCE_TPU:
                res = self.gang.reservation(pod.namespace, pod.group.name)
                if res is None:
                    raise ExtenderError(
                        f"{key}: gang reservation dissolved (TTL/fault); "
                        "scheduler will re-run the cycle"
                    )
                if not self.gang.assignable(res, count):
                    res = None  # overflow replica: normal placement
            if res is not None:
                terminating = self.gang.terminating_victims_of(res)
                if terminating:
                    # preemption executed but victims still hold the chips:
                    # no member may start until their pod objects are gone
                    raise ExtenderError(
                        f"{key}: gang waiting for {len(terminating)} "
                        "preemption victim(s) to finish terminating; "
                        "scheduler will re-run the cycle"
                    )
            if res is not None:
                try:
                    plan = self.gang.plan_for_bind(res, pod, node_name)
                except GangError as e:
                    raise ExtenderError(str(e)) from e
            else:
                plan = self._plan_chips(view, resource, count)
            if plan is None:
                raise ExtenderError(
                    f"{key}: node {node_name} can no longer fit {count} x {resource}"
                )
            device_ids = self._mint_device_ids(view, resource, plan)
            if res is not None:
                # two-phase preemption: the first member to bind executes
                # the eviction plan recorded at filter time — but only
                # after this member's commit is pre-validated, so a bind
                # that would fail anyway (chip went unhealthy, chip taken
                # by a non-victim) never costs the victims their chips
                self._execute_pending_preemption(res, view, device_ids)
            env: dict[str, str] = {}
            if self.tenants is not None:
                from tpukube.device.tpu import ENV_KUBE_TENANT

                # tenant attribution rides the alloc annotation so the
                # TenantLedger (and a restarted extender's rebuild)
                # charge the right owner
                env[ENV_KUBE_TENANT] = self.tenants.tenant_of(pod)
            if res is not None:
                # gang context for the in-pod runtime (rides the alloc
                # annotation / downward API — the device plugin's Allocate
                # only sees device ids, so megascale-style multislice
                # coordination env cannot come from the node agent)
                from tpukube.device.tpu import (
                    ENV_GANG_NUM_SLICES,
                    ENV_GANG_SLICE_INDEX,
                    ENV_GANG_SLICES,
                )

                sids = sorted(res.slice_coords)
                env[ENV_GANG_NUM_SLICES] = str(len(sids))
                env[ENV_GANG_SLICES] = ",".join(sids)
                env[ENV_GANG_SLICE_INDEX] = str(sids.index(view.info.slice_id))
            alloc = AllocResult(
                pod_key=key,
                node_name=node_name,
                device_ids=device_ids,
                coords=sorted(set(plan)),
                env=env,
                priority=pod.priority,
                uid=uid or cached_uid or "",
            )
            self.state.commit(alloc)  # StateError on lost race
            if res is not None:
                try:
                    committed_now = self.gang.on_bound(
                        res, key, plan, node_name
                    )
                except GangError as e:
                    # reservation changed between plan and commit: undo
                    self.state.release(key)
                    raise ExtenderError(str(e)) from e
                if committed_now and self.trace is not None:
                    # timeline span: this bind assembled the quorum
                    self.trace.span(
                        "gang_commit", key,
                        gang=f"{res.namespace}/{res.group.name}",
                        members=len(res.assigned),
                        latency_s=res.commit_latency,
                    )
                if self.binder is not None:
                    # _handle_bind's effector undo needs to know whether
                    # THIS bind committed the gang (keyed, since other
                    # binds may interleave once the decision lock drops);
                    # proven by the interprocedural caller-check: every
                    # intra-class bind() call site holds _decision_lock
                    self._bind_gang_info[key] = (res, committed_now)
            with self._pending_lock:
                self._pending.pop(key, None)
            self.binds_total += 1
            log.info("bound %s -> %s %s", key, node_name, device_ids)
            return alloc
        finally:
            self._observe_latency("bind", time.monotonic() - t0)

    def _mint_device_ids(
        self, view: NodeView, resource: str, plan: list[TopologyCoord]
    ) -> list[str]:
        if resource == RESOURCE_TPU:
            return [
                make_device_id(self._index_at(view, coord)) for coord in plan
            ]
        # vTPU: mint the lowest UNUSED share index per chip — a count would
        # re-issue a released id while its sibling is still allocated
        n = view.shares_per_chip
        ids = []
        taken: dict[int, set[int]] = {}
        for coord in plan:
            index = self._index_at(view, coord)
            if index not in taken:
                taken[index] = set(view.used_frac_ks(index))
            k = next((i for i in range(n) if i not in taken[index]), None)
            if k is None:
                raise ExtenderError(f"chip {index}: shares exhausted mid-mint")
            taken[index].add(k)
            ids.append(make_device_id(index, (k, n)))
        return ids

    # -- batch-driver hooks (sched/cycle.py; sim driver + pod informer) -----
    def admit(self, pod: PodInfo) -> bool:
        """Admit a pending pod into the scheduling queue ahead of its
        /filter webhook (pod-informer feed / sim batch driver). No-op
        without batching — the webhook path needs no pre-admission.
        Returns True when the pod actually entered the queue (False:
        batching off, tenancy refusal, or a live plan already exists —
        informer re-deliveries must not replan an assumed allocation).

        With the tenancy plane on, the admission gate runs HERE too —
        at enqueue time, against pre-drain usage — so a shed burst
        never even enters the queue (the plan-time gate inside the
        planning arms stays authoritative for quota races within a
        drain)."""
        if self.cycle is None:
            return False
        with self._decision_lock:
            if self.cycle.plan_is_live(pod):
                # informer re-delivery of an already-planned pod: no
                # re-enqueue, and — checked FIRST — no tenancy gate
                # run, which would journal a phantom refusal against a
                # pod whose own assumed usage already fills its quota
                return False
            if self.tenants is not None:
                try:
                    ask = self.device_request(pod)
                except ExtenderError:
                    ask = None  # planning reports the schema error
                if ask is not None and self.tenants.admit(
                    pod, ask[0], ask[1]
                ) is not None:
                    # refused and journaled, not enqueued — but the pod
                    # IS pending (the feed retries), so the starvation
                    # stats must see its first-admit stamp: a tenant
                    # shed for hours accumulates age here too, not
                    # just on the webhook path
                    self.cycle.note_pending(pod.key())
                    return False
            self.cycle.enqueue(pod)
            self._note_decision(pod.key(), "admit",
                                queue_depth=self.cycle.queue_depth())
            return True

    def plan_pending(self) -> int:
        """Drive batch cycles until the admitted queue drains; returns
        pods planned. The sim batch driver's entry point — webhook
        arrivals plan through handle('filter') instead."""
        if self.cycle is None:
            return 0
        with self._decision_lock:
            return self.cycle.run_pending()

    def planned_node(self, pod_key: str) -> Optional[str]:
        """The batch plan's predicted node for a pod (None = no live
        plan / plan found the pod unschedulable). Drivers use it to
        issue the /bind the plan anticipates."""
        if self.cycle is None:
            return None
        with self._decision_lock:
            return self.cycle.planned_node(pod_key)

    # -- pod lifecycle ------------------------------------------------------
    def release(self, pod_key: str) -> None:
        self.handle("release", {"pod_key": pod_key})

    def release_many(self, pod_keys: list[str]) -> None:
        """Batched releases (the lifecycle resync's flush surface — the
        ShardRouter fans these out per replica; here each is the same
        recorded release decision the per-key path dispatches)."""
        for key in pod_keys:
            self.handle("release", {"pod_key": key})

    def upsert_nodes_many(self, items: list[dict[str, Any]]) -> list[Any]:
        """Batched node ingest in the ShardRouter's surface shape: one
        ``upsert_nodes`` decision for the whole batch (the bulk
        cold-start fast path when ``bulk_ingest_enabled``), per-item
        results positionally."""
        return self.handle("upsert_nodes", {"items": list(items)})[
            "results"]

    # -- atomic webhook dispatch --------------------------------------------
    def handle(self, kind: str, body: Any) -> Any:
        """Process one decision request body and return the wire response.

        Every decision path — the HTTP handlers, the sim harness's direct
        releases, trace replay — comes through here: mutation and trace
        recording happen under one lock, so trace order IS application
        order even with releases arriving from threads other than the
        webhook loop (the round-1 determinism caveat this removes).

        Schema errors raise ``kube.KubeSchemaError`` before any mutation;
        the HTTP layer maps them to 400 without recording.
        """
        if kind == "bind":
            return self._handle_bind(body)
        if kind == "filter":
            reason = self._degraded_reason()
            if reason is not None:
                # fail safe BEFORE any mutation or trace record (the
                # schema-error contract): no reservation is created, no
                # preemption planned, and the refusal replays as
                # nothing because it changed nothing
                pod, nodes, names = kube.parse_extender_args(body)
                mk = (kube.filter_result if nodes is not None
                      else kube.filter_result_names)
                self._emit_event(
                    "DegradedMode", "extender/filter",
                    f"failing filter requests safe: {reason}",
                )
                self._note_decision(
                    pod.key(), "refusal", kind="degraded",
                    reason=f"degraded mode: {reason}",
                )
                return mk([], {}, error=f"degraded mode: {reason}")
        with self._decision_lock:
            if kind == "filter":
                pod, nodes, names = kube.parse_extender_args(body)
                if nodes is None and names is None:
                    # NodesCached body: the candidate set is every node
                    # this planner knows (the cached tuple — no O(nodes)
                    # list rebuild per webhook)
                    names = self.state.node_names()
                mk = (kube.filter_result if nodes is not None
                      else kube.filter_result_names)
                # per-tenant admission latency (tenancy v2): the whole
                # filter decision's wall, charged to the pod's tenant —
                # the tpukube_tenant_admission_seconds histogram the
                # per-tenant burn monitor slides its windows over
                tt0 = (time.monotonic() if self.tenants is not None
                       else None)
                try:
                    if self.cycle is not None:
                        # batch mode: admit + plan (one snapshot per
                        # cycle), answer from the plan
                        t0 = time.monotonic()
                        try:
                            response: Any = self.cycle.filter_response(
                                pod, nodes, names
                            )
                        finally:
                            self._observe_latency(
                                "filter", time.monotonic() - t0
                            )
                    else:
                        feasible, failed = self.filter(
                            pod, raw_nodes=nodes, node_names=names
                        )
                        response = mk(feasible, failed)
                except (ExtenderError, GangError, StateError,
                        codec.CodecError) as e:
                    response = mk([], {}, error=str(e))
                    # the refusal the scheduler will see — tenancy
                    # verdicts additionally recorded their own stage
                    # at the gate
                    self._note_decision(pod.key(), "refusal",
                                        kind="filter_error",
                                        reason=str(e))
                    if self.capacity is not None:
                        # stranded-demand forensics: root-cause the
                        # legacy-path refusal (fragmented / capacity /
                        # quota / shed / unhealthy / dcn-ineligible)
                        self.capacity.note_refusal(pod, str(e))
                if self.tenants is not None and tt0 is not None:
                    self.tenants.observe_admission(
                        self.tenants.tenant_of(pod),
                        time.monotonic() - tt0,
                    )
            elif kind == "prioritize":
                pod, nodes, names = kube.parse_extender_args(body)
                if nodes is None and names is None:
                    names = self.state.node_names()  # NodesCached body
                scores = None
                if self.cycle is not None:
                    if nodes is not None:
                        names = self._ingest_nodes(nodes)
                        nodes = None
                    t0 = time.monotonic()
                    scores = self.cycle.prioritize_response(
                        pod, list(names or [])
                    )
                    if scores is not None:
                        self._observe_latency(
                            "prioritize", time.monotonic() - t0
                        )
                if scores is None:
                    try:
                        scores = self.prioritize(
                            pod, raw_nodes=nodes, node_names=names
                        )
                    except (ExtenderError, GangError, StateError,
                            codec.CodecError) as e:
                        log.warning("prioritize failed: %s", e)
                        scores = {}
                response = kube.host_priority_list(scores)
            elif kind == "release":
                pod_key = body["pod_key"]
                self.state.release(pod_key)
                self.gang.on_release(pod_key)
                if self.cycle is not None:
                    self.cycle.on_release(pod_key)
                with self._pending_lock:
                    self._pending.pop(pod_key, None)
                self._note_decision(pod_key, "release")
                response = None
            elif kind == "victim_gone":
                # an eviction victim's pod object is confirmed gone
                # (EvictionExecutor GET-confirm, or the lifecycle watch's
                # DELETED event): unmask its chips, unblock gated gangs.
                # A recorded decision so captures replay deterministically
                # — the gate's state changes only through the trace.
                response = {
                    "cleared": self.gang.on_victim_gone(body["pod_key"])
                }
            elif kind == "reconcile":
                response = {
                    "changed": self._reconcile_devices(
                        body["pod_key"], list(body["devices"])
                    )
                }
            elif kind == "upsert_node":
                # out-of-band node-annotation refresh (nodeCacheCapable
                # mode: webhooks carry names only, so topology updates
                # arrive through this recorded decision instead)
                try:
                    response = {"ours": self.state.upsert_node(
                        body["name"], dict(body.get("annotations") or {})
                    )}
                except (codec.CodecError, StateError) as e:
                    response = {"error": str(e)}
            elif kind == "upsert_nodes":
                # batched fleet ingest (ISSUE 15): ONE recorded decision
                # for the whole batch; per-item results ride the
                # response positionally in the per-item shape
                items = list(body.get("items") or [])
                if self.bulk_ingest:
                    results = self.state.ingest_nodes(items)
                    # drain the deferred decodes off the serving path,
                    # exactly like the journal recovery's warmer
                    self.state.maybe_start_warmer()
                else:
                    results = []
                    for item in items:
                        try:
                            results.append({
                                "ours": self.state.upsert_node(
                                    item["name"],
                                    dict(item.get("annotations") or {}),
                                )
                            })
                        except (codec.CodecError, StateError) as e:
                            results.append({"error": str(e)})
                response = {"results": results}
            else:
                raise ValueError(f"unknown decision kind {kind!r}")
            if self.trace is not None:
                self.trace.record(kind, body, response)
            if self.journal is not None:
                self._maybe_checkpoint()
            if self.capacity is not None:
                # amortized flight-recorder hook (the checkpoint
                # seam's pattern): a scheduling-clock read per
                # decision, a real sample only on interval expiry
                self.capacity.maybe_sample()
            if self.drain is not None:
                # amortized drain choreography: budgeted migrate-or-
                # preempt progress rides the decision path under the
                # same lock, exactly like checkpoints and capacity
                # samples (a clock read when no drain is active)
                self.drain.maybe_tick()
            if self.autoscaler is not None:
                self.autoscaler.maybe_tick()
            return response

    def checkpoint_doc(self) -> dict:
        """The full Checkpoint capture (ledger + gang reservations +
        the cached scheduling snapshot + the WAL position they cover).
        Callers hold the decision lock — or run before serving
        (recovery) — so the capture is atomic with respect to every
        mutation path; the build is in-memory only (node lines
        memoized per payload; still-lazy nodes captured as byte refs
        into the previous checkpoint file), serialization and disk
        belong to the journal's drain thread."""
        if self.journal is None:
            raise RuntimeError(
                "checkpoint capture requires the journal "
                "(journal_enabled)")
        state_head, node_entries = self.state.checkpoint_doc(
            self._ckpt_cache
        )
        head = {
            "v": 2,
            "ts": time.time(),
            "wal_seq": self.journal.seq(),
            "state": state_head,
            "gang": self.gang.checkpoint_doc(),
        }
        snap = self.snapshots.peek()
        if snap is not None:
            # the seedable scheduling snapshot: a warm restart installs
            # it directly, so the first lookups HIT instead of forcing
            # the O(chips) rebuild that would drag every lazy node in
            snap_doc: dict[str, dict] = {}
            for sid, ss in snap.slices.items():
                sd = {
                    "occ": [list(c) for c in ss.occupied],
                    "res": [list(c) for c in ss.reserved],
                    "unh": [list(c) for c in ss.unhealthy],
                    "term": [list(c) for c in ss.terminating],
                    "brk": [[list(a), list(b)] for a, b in ss.broken],
                    "used": ss.used_shares,
                    "total": ss.total_shares,
                }
                if ss.cordoned:
                    # only-when-non-empty: with the drain flag off the
                    # checkpoint bytes stay identical to the pre-drain
                    # layout (the off-is-off golden)
                    sd["crd"] = [list(c) for c in ss.cordoned]
                snap_doc[sid] = sd
            head["snap"] = snap_doc
        return {
            "head": head,
            "node_entries": node_entries,
            "old_fd": self.state.lazy_fd_dup(),
        }

    def _maybe_checkpoint(self) -> None:
        """Periodic checkpoint capture, amortized onto the decision
        path (a time check per decision; the capture itself runs at
        checkpoint_interval cadence or after a WAL rotation)."""
        if not self.journal.checkpoint_due(self.clock.monotonic()):
            return
        self.journal.request_checkpoint(self.checkpoint_doc())

    def _handle_bind(self, body: Any) -> Any:
        """The bind decision, split around the external effector: ledger
        mutation + trace record run under the decision lock; the binder's
        apiserver I/O (Binding POST + annotation PATCH, potentially slow)
        runs OUTSIDE it so one apiserver hiccup cannot stall every
        concurrent filter/prioritize. A failed effector undoes through a
        regular recorded ``release`` decision — the trace then replays as
        (bind ok, release), which IS the ledger's true history; only the
        wire response reports the failure to the scheduler for a retry."""
        name, ns, uid, node = kube.parse_binding_args(body)
        key = f"{ns}/{name}"
        bt0 = time.monotonic()
        degraded = self._degraded_reason()
        if degraded is not None:
            # same fail-safe contract as filter: refused before any
            # mutation, nothing recorded — a bind the effector could
            # not deliver anyway must not touch the ledger or execute
            # a preemption plan
            self._emit_event(
                "DegradedMode", "extender/bind",
                f"failing bind requests safe: {degraded}",
            )
            self._note_decision(key, "refusal", kind="degraded",
                                reason=f"degraded mode: {degraded}")
            return kube.binding_result(f"{key}: degraded mode: {degraded}")
        blocked = self._precheck_preemption(key)
        if blocked:
            # refused BEFORE any mutation, so nothing is recorded in
            # the TRACE (same contract as schema errors): the plan
            # stays pending and the reservation TTLs out if the PDB
            # never lifts — no victim is half-evicted, no gang
            # half-binds. The refusal still lands in the provenance
            # chain — a pod stuck behind a PDB is exactly the incident
            # `explain` must answer.
            reason = (
                f"{key}: preemption plan refused — PodDisruptionBudget "
                f"blocks eviction of {sorted(blocked)[:3]}"
            )
            self._note_decision(key, "refusal", kind="pdb_precheck",
                                reason=reason)
            return kube.binding_result(reason)
        alloc = None
        gang_info = None
        with self._decision_lock:
            planned = None
            if self.cycle is not None:
                # batch mode: consume the plan's assumed allocation (or
                # its planned error) instead of re-planning; a miss —
                # no plan, deferred preemption, node disagreement —
                # falls through to the legacy bind below
                t0 = time.monotonic()
                planned = self.cycle.take_for_bind(key, uid, node)
                if planned is not None:
                    self._observe_latency("bind", time.monotonic() - t0)
                    if self.phase_hist is not None:
                        # commit phase: consuming the plan's assumed
                        # allocation (or its planned error) at /bind
                        self.phase_hist.labels(phase="commit").observe(
                            time.monotonic() - t0
                        )
            try:
                if planned is not None:
                    verdict, payload = planned
                    if verdict == "ok":
                        alloc = payload
                        gang_info = self._bind_gang_info.pop(key, None)
                        response: Any = kube.binding_result()
                        response["Annotations"] = {
                            codec.ANNO_ALLOC: codec.encode_alloc(alloc)
                        }
                    else:
                        response = kube.binding_result(payload)
                else:
                    alloc = self.bind(name, ns, uid, node)
                    # consume THIS bind's gang marker under the same
                    # lock; a FAILED bind must not pop (the key may
                    # belong to another in-flight bind's pending
                    # effector)
                    gang_info = self._bind_gang_info.pop(key, None)
                    # the alloc annotation rides back to the
                    # harness/apiserver-writer
                    response = kube.binding_result()
                    response["Annotations"] = {
                        codec.ANNO_ALLOC: codec.encode_alloc(alloc)
                    }
            except (ExtenderError, GangError, StateError,
                    codec.CodecError) as e:
                # an errored response must NEVER run the effector, even
                # when bind() itself succeeded and a later step threw —
                # the scheduler will retry a bind we told it failed
                alloc = None
                response = kube.binding_result(str(e))
            if alloc is not None and self.cycle is not None:
                # the pod bound (plan-served OR legacy fallback):
                # retire its first-admit stamp so the pending-age
                # starvation stats stop counting it
                self.cycle.on_bound(key)
            if self.decisions is not None and self.decisions.wants(key):
                err = (response.get("Error")
                       if isinstance(response, dict) else None)
                self.decisions.record(
                    key, "bind", node=node, ok=not err,
                    error=err or None,
                    served_from=("plan" if planned is not None
                                 else "legacy"),
                )
            if self.trace is not None:
                self.trace.record("bind", body, response)
            if self.journal is not None:
                self._maybe_checkpoint()
        if self.tenants is not None and alloc is not None:
            # per-tenant commit latency: the whole successful bind
            # decision's wall, charged to the allocation's tenant
            self.tenants.observe_commit(
                self.tenants.tenant_of_alloc(alloc),
                time.monotonic() - bt0,
            )
        if alloc is None or self.binder is None:
            return response
        try:
            self.binder(alloc)
        except Exception as e:
            # the Binding POST/annotation PATCH failed: the pod is NOT
            # bound on the cluster (annotation-first ordering guarantees
            # partial failures leave it Pending), so the ledger must not
            # claim it is. Preemption evictions already executed stand:
            # the victims were released either way.
            log.error("bind effector for %s failed: %s", key, e)
            self._emit_event(
                "BindFailed", f"pod/{key}",
                f"apiserver bind failed after a successful ledger "
                f"commit; undone for retry: {e}",
            )
            with self._decision_lock:
                # undo atomically w.r.t. other binds (which also hold the
                # decision lock): a sibling member interleaving between
                # the uncommit and the release could otherwise re-commit
                # a quorum that counts this phantom member
                if gang_info is not None and gang_info[1]:
                    # this very bind committed the gang: the quorum never
                    # truly assembled — revert flag + latency sample
                    self.gang.undo_commit(gang_info[0])
                self.handle("release", {"pod_key": key})
                self.binds_total -= 1  # the bind did not survive
                # the earlier bind record said ok=True (the ledger
                # commit succeeded); the pod is NOT bound on the
                # cluster — without this stage its explain would read
                # "bound ... released" for a pod Pending on retry
                self._note_decision(
                    key, "bind", node=node, ok=False,
                    error=f"apiserver bind failed: {e}",
                    served_from="effector",
                )
            return kube.binding_result(f"{key}: apiserver bind failed: {e}")
        return response

    def _precheck_preemption(self, pod_key: str) -> list[str]:
        """PDB dry-run for the eviction plan a bind for ``pod_key`` would
        execute: the victim pod keys a PodDisruptionBudget blocks right
        now ([] = proceed). External I/O, so it runs in _handle_bind
        OUTSIDE the decision lock; no precheck wired (or an errored
        dry-run) means proceed — the executor's forever-retry then covers
        the raced case exactly as before."""
        if self.evict_precheck is None:
            return []
        with self._pending_lock:
            entry = self._pending.get(pod_key)
        if entry is None or entry[0].group is None:
            return []
        pod = entry[0]
        res = self.gang.reservation(pod.namespace, pod.group.name)
        if res is None:
            return []
        try:
            ask = self.device_request(pod)
        except ExtenderError:
            return []  # bind() will surface the real error
        # mirror bind()'s routing: an overflow replica of a full gang
        # binds as a normal pod and executes no preemption — its bind
        # must not be refused for a PDB that only blocks the gang's plan
        if ask is None or not self.gang.assignable(res, ask[1]):
            return []
        victim_pods = self._victim_pod_keys(
            self.gang.peek_pending_victims(res)
        )
        blocked = []
        for vk in victim_pods:
            try:
                if self.evict_precheck(vk) is False:
                    blocked.append(vk)
            except Exception as e:
                # cannot determine (old apiserver, transient error):
                # proceed — refusing would wedge preemption on noise
                log.warning("eviction precheck for %s failed: %s", vk, e)
        return blocked

    def _reconcile_devices(self, pod_key: str, device_ids: list[str]) -> bool:
        """Fold the kubelet's ACTUAL device choice into the ledger when it
        diverged from the plan (reported through the pod's ``alloc-actual``
        annotation — apiserver.AllocReconcileLoop drives this as a recorded
        ``reconcile`` decision). The container is already running on those
        chips, so reality wins: the planned allocation is released, the
        actual one committed, and gang bookkeeping follows. Returns True if
        the ledger changed."""
        from tpukube.core.types import parse_device_id

        alloc = self.state.allocation(pod_key)
        if alloc is None:
            log.warning("reconcile for %s: no allocation in ledger", pod_key)
            return False
        if sorted(alloc.device_ids) == sorted(device_ids):
            return False
        view = self.state.node(alloc.node_name)
        if view is None:
            log.warning("reconcile for %s: node %s unknown",
                        pod_key, alloc.node_name)
            return False
        try:
            coords = sorted({
                view.chip(parse_device_id(did)[0]).coord
                for did in device_ids
            })
        except (ValueError, KeyError) as e:
            log.warning("reconcile for %s: bad actual ids %s: %s",
                        pod_key, device_ids, e)
            return False
        # A report naming chips the ledger shows held by ANOTHER pod is
        # wrong (stale, or a misattributed divergence after an agent
        # restart) — refuse rather than evict a running pod's entry.
        held_by_others = [
            did for did in device_ids
            if did in view.used_ids and did not in alloc.device_ids
        ]
        if held_by_others:
            log.warning(
                "reconcile for %s refused: %s already held by other pods",
                pod_key, held_by_others,
            )
            return False
        self.state.release(pod_key)
        actual = AllocResult(
            pod_key=pod_key,
            node_name=alloc.node_name,
            device_ids=sorted(device_ids),
            coords=coords,
            env=alloc.env,
            priority=alloc.priority,
            uid=alloc.uid,
        )
        try:
            self.state.commit(actual)
        except StateError:
            # never leave the pod ledger-less: restore the planned entry
            self.state.commit(alloc)
            log.warning("reconcile for %s: commit of %s failed; restored "
                        "planned allocation", pod_key, sorted(device_ids))
            return False
        self.gang.reassign(pod_key, coords)
        log.warning(
            "reconciled %s on %s: kubelet allocated %s (planned %s)",
            pod_key, alloc.node_name, sorted(device_ids),
            sorted(alloc.device_ids),
        )
        return True

    # -- inspection (tpukubectl + /state endpoints) --------------------------
    def topology_snapshot(self) -> dict[str, Any]:
        """Cluster topology + occupancy as plain JSON (for tpukubectl topo).
        Per-slice sections carry the slice-local coord sets; the top-level
        fields aggregate across slices (mesh_dims is the sole slice's dims
        on a single-slice cluster, null otherwise)."""
        snap = self.snapshots.current()
        slice_ids = snap.slice_ids()
        per_slice: dict[str, dict[str, Any]] = {}
        for sid in slice_ids:
            ss = snap.slice(sid)
            per_slice[sid] = {
                "occupied": ss.occupied,
                "reserved": ss.reserved,
                "unhealthy": ss.unhealthy,
                "broken": sorted(ss.broken),
            }
        nodes = []
        for name in self.state.node_names():
            view = self.state.node(name)
            if view is None:
                continue
            s = per_slice[view.info.slice_id]
            chips = []
            for chip in view.info.chips:
                status = (
                    "unhealthy" if chip.coord in s["unhealthy"]
                    else "allocated" if chip.coord in s["occupied"]
                    else "reserved" if chip.coord in s["reserved"]
                    else "free"
                )
                chips.append({
                    "index": chip.index,
                    "coord": list(chip.coord),
                    "status": status,
                    "used_shares": view.used_share_count(chip.index),
                    "shares": view.shares_per_chip,
                })
            nodes.append({
                "name": name, "slice": view.info.slice_id, "chips": chips,
                # operators spot table-fallback nodes (static HBM/core
                # guesses) at a glance in tpukubectl topo
                "source": view.info.source,
            })
        return {
            "mesh_dims": (
                list(snap.slice(slice_ids[0]).mesh.dims)
                if len(slice_ids) == 1 else None
            ),
            "utilization_percent": round(100.0 * self.state.utilization(), 2),
            "chips_total": sum(len(n["chips"]) for n in nodes),
            "chips_allocated": sum(len(s["occupied"]) for s in per_slice.values()),
            "chips_reserved_unbound": sum(
                len(s["reserved"] - s["occupied"]) for s in per_slice.values()
            ),
            "chips_unhealthy": sum(
                len(s["unhealthy"]) for s in per_slice.values()
            ),
            "links_down": [
                [list(a), list(b)]
                for s in per_slice.values() for a, b in s["broken"]
            ],
            "slices": [
                {
                    "id": sid,
                    "mesh_dims": list(snap.slice(sid).mesh.dims),
                    "utilization_percent": round(
                        100.0 * snap.slice(sid).utilization, 2
                    ),
                    # epoch-cached free-space health (snapshot-derived):
                    # how shattered the slice's free space is, and the
                    # biggest gang box it could still take
                    "fragmentation": round(
                        snap.slice(sid).fragmentation(), 4
                    ),
                    "largest_free_box_chips": snap.slice(
                        sid).largest_free_box(),
                    "links_down": [
                        [list(a), list(b)] for a, b in per_slice[sid]["broken"]
                    ],
                }
                for sid in slice_ids
            ],
            "nodes": nodes,
        }

    def alloc_snapshot(self) -> list[dict[str, Any]]:
        """Committed allocations as plain JSON (for tpukubectl alloc)."""
        return [
            {
                "pod": a.pod_key,
                "node": a.node_name,
                "devices": list(a.device_ids),
                "coords": [list(c) for c in a.coords],
                "priority": a.priority,
            }
            for a in sorted(self.state.allocations(), key=lambda a: a.pod_key)
        ]

    def gang_snapshot(self) -> list[dict[str, Any]]:
        """Live gang reservations as plain JSON (for tpukubectl gangs)."""
        out = []
        for res in self.gang.snapshot():
            out.append({
                "namespace": res.namespace,
                "group": res.group.name,
                "min_member": res.group.min_member,
                "members_bound": len(res.assigned),
                "committed": res.committed,
                "priority": res.priority,
                "spans_dcn": res.spans_dcn,
                # why an assembling gang is not binding: victims planned
                # (preemption not yet executed) or still terminating —
                # both through the manager's locked accessors
                "victims_pending": len(
                    self.gang.peek_pending_victims(res)
                ),
                "victims_terminating": len(
                    self.gang.terminating_victims_of(res)
                ),
                "slices": {
                    sid: [list(c) for c in sorted(coords)]
                    for sid, coords in sorted(res.slice_coords.items())
                },
            })
        return sorted(out, key=lambda g: (g["namespace"], g["group"]))

    # -- restart story (SURVEY.md §6 checkpoint/resume) ----------------------
    def rebuild_from_pods(self, pods: list[dict[str, str]]) -> int:
        """Reconstruct ledger AND gang reservations from pod annotations
        (each item is one pod's annotation dict) after an extender restart.

        Restoring only per-pod allocations would silently downgrade running
        gangs to free-standing pods: a later preemption could then evict
        individual members, violating all-or-nothing death. The pod-group
        annotations persist gang identity, so rebuild it here.
        """
        restored = self.state.rebuild_from_pods(pods)
        members: dict[tuple[str, str], list] = {}  # (ns, group) -> [(alloc, group)]
        for annotations, alloc in restored:
            try:
                group = codec.pod_group_from_annotations(annotations)
            except codec.CodecError as e:
                # one pod's malformed gang annotation must not abort the
                # whole cluster's state reconstruction
                log.warning(
                    "pod %s: undecodable pod-group annotation (%s); "
                    "restored as non-gang", alloc.pod_key, e,
                )
                continue
            if group is None:
                continue
            ns = alloc.pod_key.split("/", 1)[0]
            members.setdefault((ns, group.name), []).append((alloc, group))
        for (ns, _), entries in members.items():
            allocs = [a for a, _ in entries]
            self.gang.restore(ns, entries[0][1], allocs)
        return len(restored)


# -- aiohttp application ----------------------------------------------------

def make_app(
    extender: Extender, reconcile=None, evictions=None,
    node_refresh=None, lifecycle=None, auth_token: Optional[str] = None,
    informer=None, client_max_size: Optional[int] = None,
) -> web.Application:
    """``reconcile``/``evictions``/``node_refresh``/``lifecycle`` are the
    daemon's loops, exported on /metrics when present; ``informer`` is
    the shared PodInformer whose stream liveness /statusz reports (falls
    back to ``lifecycle`` when the loops run standalone).

    ``auth_token`` gates every route except /healthz and /metrics behind
    ``Authorization: Bearer <token>``: /bind mutates the ledger, creates
    Bindings, and executes preemption; /state and /trace disclose the
    whole cluster's placement — none of that may answer an
    unauthenticated request. (/healthz stays open for kubelet probes,
    /metrics for Prometheus scrapes; both are read-only and
    non-disclosing.) Transport security/mTLS is the TLS layer's job —
    cli.main_extender builds the SSLContext; this is the
    application-level check that also protects plain-HTTP dev setups and
    defends in depth behind TLS.

    ``client_max_size`` overrides aiohttp's 1 MiB request-body cap —
    the shard worker's batched transport routes (a whole fleet's
    upsert, a wave of admits) legitimately exceed it; None keeps the
    aiohttp default for the standalone daemon."""
    app = (web.Application(client_max_size=client_max_size)
           if client_max_size is not None else web.Application())

    if auth_token:
        expected = f"Bearer {auth_token}".encode()

        @web.middleware
        async def bearer_auth(request: web.Request, handler):
            if request.path in ("/healthz", "/metrics"):
                return await handler(request)
            got = request.headers.get("Authorization", "")
            # constant-time compare on BYTES: the token is a credential,
            # and the str overload raises on non-ASCII input (a crafted
            # header must get a 401, not a 500)
            import hmac
            if not hmac.compare_digest(
                got.encode("utf-8", "surrogateescape"), expected
            ):
                raise web.HTTPUnauthorized(
                    text="missing or invalid bearer token",
                    headers={"WWW-Authenticate": "Bearer"},
                )
            return await handler(request)

        app.middlewares.append(bearer_auth)

    async def _json(request: web.Request) -> Any:
        try:
            return await request.json()
        except json.JSONDecodeError as e:
            raise web.HTTPBadRequest(text=f"bad JSON: {e}")

    def _webhook(kind: str):
        # mutation + trace record are one atomic step inside handle()
        async def handler(request: web.Request) -> web.Response:
            body = await _json(request)
            try:
                return web.json_response(extender.handle(kind, body))
            except kube.KubeSchemaError as e:
                raise web.HTTPBadRequest(text=str(e))

        return handler

    filter_handler = _webhook("filter")
    prioritize_handler = _webhook("prioritize")
    bind_handler = _webhook("bind")

    async def state_topology(request: web.Request) -> web.Response:
        return web.json_response(extender.topology_snapshot())

    async def state_allocs(request: web.Request) -> web.Response:
        return web.json_response(extender.alloc_snapshot())

    async def state_gangs(request: web.Request) -> web.Response:
        return web.json_response(extender.gang_snapshot())

    async def trace_handler(request: web.Request) -> web.Response:
        if extender.trace is None:
            raise web.HTTPNotFound(text="tracing disabled (set trace_capacity)")
        try:
            since = int(request.query.get("since", 0))
        except ValueError:
            raise web.HTTPBadRequest(text="since must be an integer")
        return web.json_response(extender.trace.events(since_seq=since))

    async def events_handler(request: web.Request) -> web.Response:
        # behind the bearer middleware: events name pods/gangs/victims
        q = request.query
        since: Any = None
        if q.get("since"):
            try:
                since = float(q["since"])
            except ValueError:
                raise web.HTTPBadRequest(text="since must be a unix ts")
        return web.json_response(extender.events.events(
            reason=q.get("reason") or None,
            pod=q.get("pod") or None,
            node=q.get("node") or None,
            since=since,
        ))

    async def explain_handler(request: web.Request) -> web.Response:
        # behind the bearer middleware: provenance discloses placement,
        # candidate sets, and tenant shares
        if extender.decisions is None:
            raise web.HTTPNotFound(
                text="decision provenance disabled (set decisions_enabled)"
            )
        pod = request.query.get("pod", "")
        if not pod:
            raise web.HTTPBadRequest(text="pod query parameter required "
                                          "(namespace/name)")
        if "/" not in pod:
            pod = f"default/{pod}"
        return web.json_response(extender.decisions.explain(pod))

    async def capacity_handler(request: web.Request) -> web.Response:
        # behind the bearer middleware: samples disclose utilization,
        # tenant shares, and the stranded-demand ledger
        if extender.capacity is None:
            raise web.HTTPNotFound(
                text="capacity analytics disabled (set capacity_enabled)"
            )
        from tpukube.obs.capacity import parse_since

        q = request.query
        since: Any = None
        if q.get("since"):
            try:
                since = parse_since(q["since"])
            except ValueError:
                raise web.HTTPBadRequest(
                    text="since must be a unix ts or duration (15m, 2h)"
                )
        if since is not None and since < 1e9:
            # relative window: anchored to the newest sample's wall ts
            # (the events/CLI relative-since semantics)
            samples = extender.capacity.samples()
            newest = max((float(s.get("ts", 0.0)) for s in samples),
                         default=0.0)
            since = newest - since
        return web.json_response(extender.capacity.capacity_doc(since))

    async def capacity_probe_handler(request: web.Request) -> web.Response:
        # read-only what-if fit dry-run against the observer snapshot
        if extender.capacity is None:
            raise web.HTTPNotFound(
                text="capacity analytics disabled (set capacity_enabled)"
            )
        from tpukube.obs.capacity import parse_shape

        q = request.query
        count = shape = None
        try:
            if q.get("shape"):
                shape = parse_shape(q["shape"])
            elif q.get("count"):
                count = int(q["count"])
            else:
                raise ValueError("want shape=XxYxZ or count=N")
            cpp = int(q.get("cpp", 1))
            doc = extender.capacity.probe(count=count, shape=shape,
                                          chips_per_pod=cpp)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(doc)

    async def statusz_handler(request: web.Request) -> web.Response:
        # behind the bearer middleware like /state and /trace: the
        # pending-eviction queue and reservation summary disclose
        # placement, so /statusz is NOT a probe route
        from tpukube.obs.statusz import extender_statusz

        return web.json_response(extender_statusz(
            extender, evictions=evictions, informer=informer,
            node_refresh=node_refresh, lifecycle=lifecycle,
            reconcile=reconcile,
        ))

    app.router.add_post("/filter", filter_handler)
    app.router.add_post("/prioritize", prioritize_handler)
    app.router.add_post("/bind", bind_handler)
    _add_probe_routes(app, extender, reconcile, evictions,
                      node_refresh, lifecycle)
    app.router.add_get("/state/topology", state_topology)
    app.router.add_get("/state/allocs", state_allocs)
    app.router.add_get("/state/gangs", state_gangs)
    app.router.add_get("/trace", trace_handler)
    app.router.add_get("/events", events_handler)
    app.router.add_get("/explain", explain_handler)
    app.router.add_get("/capacity", capacity_handler)
    app.router.add_get("/capacity/probe", capacity_probe_handler)
    app.router.add_get("/statusz", statusz_handler)
    return app


def _add_probe_routes(app, extender, reconcile=None, evictions=None,
                      node_refresh=None, lifecycle=None) -> None:
    async def healthz(request: web.Request) -> web.Response:
        return web.json_response(
            {"ok": True, "nodes": extender.state.node_names()}
        )

    async def metrics(request: web.Request) -> web.Response:
        from tpukube.metrics import render_extender_metrics

        return web.Response(
            text=render_extender_metrics(
                extender, reconcile=reconcile, evictions=evictions,
                node_refresh=node_refresh, lifecycle=lifecycle,
            ),
            content_type="text/plain",
        )

    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)


def make_probe_app(extender, reconcile=None, evictions=None,
                   node_refresh=None, lifecycle=None) -> web.Application:
    """/healthz + /metrics ONLY — the mTLS deployment's second listener.

    With --tls-client-ca, the main port rejects every peer without a
    CA-signed client certificate at the handshake — which kubelet's
    httpGet probes and Prometheus scrapes cannot present. This app
    serves exactly the two read-only, non-disclosing routes over the
    separate --probe-port so probes and scrapes work while /bind,
    /state, and /trace stay behind mTLS."""
    app = web.Application()
    _add_probe_routes(app, extender, reconcile, evictions,
                      node_refresh, lifecycle)
    return app


def run_probe_server(app: web.Application, host: str, port: int):
    """Serve ``app`` from a daemon thread with its own event loop;
    returns a stop() callable. The main serving loop belongs to
    web.run_app — this is only for the auxiliary probe listener."""
    import asyncio
    import threading

    loop_box: list = []
    started = threading.Event()

    def _run() -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop_box.append(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(runner.cleanup())
            loop.close()

    thread = threading.Thread(target=_run, daemon=True,
                              name="tpukube-extender-probe")
    thread.start()
    if not started.wait(timeout=10):
        raise RuntimeError(f"probe server failed to start on :{port}")

    def stop() -> None:
        loop_box[0].call_soon_threadsafe(loop_box[0].stop)
        thread.join(timeout=5)

    return stop

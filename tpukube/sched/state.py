"""Cluster state as the extender sees it (L5 support).

SURVEY.md §6 (checkpoint/resume): the control plane is deliberately
stateless — node truth arrives in ``node-topology`` annotations with each
webhook call, and allocations live in pod annotations. The only in-memory
structure is this ledger of commitments, and it is reconstructible from pod
annotations after an extender restart (``rebuild_from_pods``), which the
tests exercise.

Occupancy accounting is share-granular: a whole-chip node is just the
n=1 case of a vTPU node, so one ledger covers both resources.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from tpukube.core import codec
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    DEFAULT_SLICE,
    AllocResult,
    ChipInfo,
    Health,
    Link,
    NodeInfo,
    TopologyCoord,
    canonical_link,
    parse_device_id,
)


log = logging.getLogger("tpukube.state")

#: per-process ledger-incarnation stream: ``allocs_since`` cursors embed
#: (pid, count) so a cursor minted against one ledger incarnation can
#: never read another incarnation's change log as its own — a restarted
#: worker process gets a fresh pid, a fresh in-process ledger a fresh
#: count, and either way the mismatch degrades to a full read.
_INCARNATIONS = itertools.count(1)


class StateError(RuntimeError):
    pass


@dataclass
class NodeView:
    """One node's decoded annotation + live occupancy, tracked at device-id
    granularity (a count would re-mint a released share's id while its twin
    is still live — ids are the unit of truth, counts are derived).
    ``share_counts`` is a per-chip cache of those derived counts, kept in
    lockstep by add_ids/remove_ids (used_share_count is the hottest call
    of every webhook — parsing ids per query was measurable)."""

    info: NodeInfo
    used_ids: set[str] = field(default_factory=set)
    share_counts: dict[int, int] = field(default_factory=dict)
    # weight each id contributed to share_counts AT COMMIT TIME — release
    # must subtract exactly that, not a recomputation: a node whose
    # shares_per_chip annotation changes under live allocations would
    # otherwise leak counts permanently
    id_weights: dict[str, int] = field(default_factory=dict)
    # verbatim annotation payload this view was decoded from; upsert_node
    # skips re-decoding when a webhook carries the identical string (hot:
    # every /filter and /prioritize re-sends every node's annotations)
    raw_payload: str = ""
    # decoded tpu.qiniu.com/health-summary annotation (obs telemetry),
    # None when the node agent predates it; the /statusz fleet rollup
    # prefers these counts and falls back to chip health otherwise
    health_summary: Optional[dict] = None

    # coord -> chip index, built on first use (views are re-created per
    # decoded annotation, never re-pointed at different chips); the bind
    # path queries this per planned coord — a linear chip scan there was
    # round-2 weak #2
    _coord_index: dict[TopologyCoord, int] = field(default_factory=dict)
    # occupancy version, bumped by add_ids/remove_ids: memoizes the
    # derived free-chip list and free-share total, which every webhook
    # recomputes per node (health changes arrive as NEW views via
    # upsert_node, so version-only invalidation is sound)
    _version: int = 0
    _free_cache: Optional[tuple[int, list[ChipInfo]]] = None
    _free_shares_cache: Optional[tuple[int, int]] = None

    @property
    def shares_per_chip(self) -> int:
        return max(1, self.info.shares_per_chip)

    def chip(self, index: int) -> ChipInfo:
        return self.info.chip_by_index(index)

    def index_at(self, coord: TopologyCoord) -> int:
        if not self._coord_index:
            self._coord_index = {c.coord: c.index for c in self.info.chips}
        try:
            return self._coord_index[coord]
        except KeyError:
            raise StateError(
                f"no chip at {coord} on {self.info.name}"
            ) from None

    def add_ids(self, ids) -> None:
        self._version += 1
        for did in ids:
            i, frac = parse_device_id(did)
            self.used_ids.add(did)
            weight = 1 if frac is not None else self.shares_per_chip
            self.id_weights[did] = weight
            self.share_counts[i] = self.share_counts.get(i, 0) + weight

    def remove_ids(self, ids) -> None:
        self._version += 1
        for did in ids:
            if did not in self.used_ids:
                continue
            i, _ = parse_device_id(did)
            self.used_ids.discard(did)
            weight = self.id_weights.pop(did, 0)
            left = self.share_counts.get(i, 0) - weight
            if left > 0:
                self.share_counts[i] = left
            else:
                self.share_counts.pop(i, None)

    def used_share_count(self, index: int) -> int:
        return self.share_counts.get(index, 0)

    def used_frac_ks(self, index: int) -> set[int]:
        out = set()
        for did in self.used_ids:
            i, frac = parse_device_id(did)
            if i == index and frac is not None:
                out.add(frac[0])
        return out

    def free_shares(self, chip: ChipInfo) -> int:
        if chip.health is not Health.HEALTHY:
            return 0
        return self.shares_per_chip - self.used_share_count(chip.index)

    def total_free_shares(self) -> int:
        cached = self._free_shares_cache
        if cached is not None and cached[0] == self._version:
            return cached[1]
        total = sum(self.free_shares(c) for c in self.info.chips)
        self._free_shares_cache = (self._version, total)
        return total

    def free_chips(self) -> list[ChipInfo]:
        """Chips with ALL shares free (placeable as whole units).
        Shared memoized list — callers must not mutate it."""
        cached = self._free_cache
        if cached is not None and cached[0] == self._version:
            return cached[1]
        out = [
            c
            for c in self.info.chips
            if self.free_shares(c) == self.shares_per_chip
        ]
        self._free_cache = (self._version, out)
        return out


def _health_only_change(a: NodeInfo, b: NodeInfo) -> bool:
    """True when the ONLY difference between two decoded node payloads
    is per-chip health (and at least one chip flipped) — the shape the
    snapshot can absorb as an O(chips-per-node) delta. Anything else
    (links, coords, ids, sharing mode, HBM/core facts, source) is
    structural and keeps the full-rebuild marker."""
    if (a.slice_id != b.slice_id
            or a.shares_per_chip != b.shares_per_chip
            or a.source != b.source
            or len(a.chips) != len(b.chips)
            or set(a.bad_links) != set(b.bad_links)):
        return False
    changed = False
    for ca, cb in zip(a.chips, b.chips):
        if (ca.chip_id != cb.chip_id or ca.index != cb.index
                or ca.coord != cb.coord or ca.hbm_bytes != cb.hbm_bytes
                or ca.num_cores != cb.num_cores):
            return False
        changed |= ca.health is not cb.health
    return changed


#: Health.value -> member (enum __call__ per chip is ~10x a dict hit,
#: and checkpoint restore runs this 40k times at 10k nodes)
_HEALTH_BY_VALUE = {h.value: h for h in Health}


def _node_doc(view: NodeView) -> dict:
    """One node's checkpoint line content (sched/journal.py): the
    DECODED view — chips, health, links, occupancy-independent facts —
    plus the raw payload for divergence compares. Mesh lives in the
    checkpoint head per slice, occupancy in the alloc list."""
    info = view.info
    return {
        "n": info.name,
        "slice": info.slice_id,
        "shares": info.shares_per_chip,
        "source": info.source,
        "chips": [
            [c.chip_id, c.index, list(c.coord), c.hbm_bytes,
             c.num_cores, c.health.value]
            for c in info.chips
        ],
        "bad": [[list(a), list(b)] for a, b in info.bad_links],
        "payload": view.raw_payload,
        "hs": view.health_summary,
    }


def _view_from_doc(doc: dict, mesh: MeshSpec) -> NodeView:
    """Rebuild a NodeView from its checkpoint line (inverse of
    ``_node_doc``; occupancy re-applies separately from the restored
    allocations). ``mesh`` is unused today but pins the contract that
    a node line is only meaningful under its slice's geometry."""
    del mesh
    chips = [
        ChipInfo(chip_id=cid, index=i, coord=TopologyCoord(*coord),
                 hbm_bytes=hbm, num_cores=cores,
                 health=_HEALTH_BY_VALUE[h])
        for cid, i, coord, hbm, cores, h in doc["chips"]
    ]
    info = NodeInfo(
        name=doc["n"], chips=chips,
        shares_per_chip=doc["shares"],
        bad_links=[canonical_link(a, b) for a, b in doc["bad"]],
        slice_id=doc["slice"], source=doc.get("source", ""),
    )
    return NodeView(info=info, raw_payload=doc["payload"],
                    health_summary=doc.get("hs"))


def _alloc_bytes(allocs: list[AllocResult]) -> int:
    """Wire-shape size of an alloc list (encoded annotation lengths) —
    the honest byte count a remote resync consumer would move. O(n)
    encodes, paid only on resync reads (Δ-sized in steady state)."""
    return sum(len(codec.encode_alloc(a)) for a in allocs)


#: shared decoder for the probe's raw_decode fast path: json.loads
#: spends two whitespace-regex matches per document on stripping the
#: (for our encoder, never-present) leading/trailing space — at 100k
#: nodes that is 200k regex calls for nothing. Payloads that DO carry
#: surrounding whitespace fall back to json.loads below.
_PROBE_DECODER = json.JSONDecoder()

#: NamedTuple's generated __new__ is a Python-level lambda; at 4 chips
#: per node the probe constructs ~400k coords per 100k-node fleet, so
#: it builds them the way _make does — straight through tuple.__new__.
_TUPLE_NEW = tuple.__new__


def _probe_node_payload(name: str, payload: str,
                        mesh_memo: Optional[dict] = None) -> dict:
    """Structural probe of a node-topology payload for the bulk ingest
    fast path: runs every validation ``decode_node_topology`` +
    ``node_from_annotations`` enforce — schema version, mesh, chip
    entries (ids/indices/coords/hbm/cores/health values), shares,
    badLinks containment + adjacency, slice id, annotation-vs-node name
    — WITHOUT constructing the ChipInfo/NodeInfo objects (the deferred
    cost lazy materialization pays on first touch). Raises CodecError
    with the same messages the full decode raises, so a malformed
    payload errors at ingest, never silently on first touch."""
    try:
        try:
            obj, end = _PROBE_DECODER.raw_decode(payload)
            if end != len(payload) and payload[end:].strip():
                raise json.JSONDecodeError("Extra data", payload, end)
        except json.JSONDecodeError:
            # leading/trailing whitespace (or junk — which re-raises
            # with loads' message): the tolerant path
            obj = json.loads(payload)
    except json.JSONDecodeError as e:
        raise codec.CodecError(f"node-topology: bad JSON: {e}") from e
    codec._check_version(obj, "node-topology")
    try:
        fragment = codec._field(obj, "mesh", "node-topology")
        # a homogeneous fleet repeats one mesh fragment per slice:
        # decode (and validate) it once per distinct fragment. The key
        # covers exactly the fields from_json reads.
        mesh = None
        memo_key = None
        if mesh_memo is not None:
            memo_key = (
                tuple(fragment["dims"]),
                tuple(fragment.get("host_block", (2, 2, 1))),
                tuple(fragment.get("torus", (False, False, False))),
            )
            mesh = mesh_memo.get(memo_key)
        if mesh is None:
            mesh = MeshSpec.from_json(fragment)
            if memo_key is not None:
                mesh_memo[memo_key] = mesh
    except (KeyError, TypeError, ValueError) as e:
        if isinstance(e, codec.CodecError):
            raise
        raise codec.CodecError(
            f"node-topology: malformed mesh: {e}") from e
    raw_chips = codec._field(obj, "chips", "node-topology")
    if not isinstance(raw_chips, list):
        raise codec.CodecError("node-topology: 'chips' must be a list")
    # ONE pass over the chip entries (this loop runs per chip of the
    # whole fleet): coord construction + every field materialization
    # decodes later, so a malformed entry fails HERE with the decode's
    # message. The "Healthy" string compare is the hot fast path — the
    # enum call validates only the rare non-healthy value (junk raises
    # the decode's exact error).
    coords: list[TopologyCoord] = []
    unhealthy: list[TopologyCoord] = []
    append_coord = coords.append
    try:
        for c in raw_chips:
            x, y, z = c["coord"]
            coord = _TUPLE_NEW(TopologyCoord,
                               (int(x), int(y), int(z)))
            append_coord(coord)
            c["id"]
            int(c["index"])
            int(c["hbm"])
            int(c.get("cores", 2))
            h = c.get("health", "Healthy")
            if h != "Healthy" and Health(h) is not Health.HEALTHY:
                unhealthy.append(coord)
    except (KeyError, TypeError, ValueError) as e:
        raise codec.CodecError(
            f"node-topology: malformed chip entry: {e}") from e
    try:
        shares = int(obj.get("sharesPerChip", 1))
    except (TypeError, ValueError) as e:
        raise codec.CodecError(
            f"node-topology: bad sharesPerChip: {e}") from e
    if shares < 1:
        raise codec.CodecError(
            f"node-topology: sharesPerChip must be >= 1, got {shares}")
    raw_links = obj.get("badLinks", [])
    if not isinstance(raw_links, list):
        raise codec.CodecError("node-topology: 'badLinks' must be a list")
    try:
        bad_links = [canonical_link(a, b) for a, b in raw_links]
    except (TypeError, ValueError) as e:
        raise codec.CodecError(
            f"node-topology: malformed badLinks entry: {e}") from e
    for a, b in bad_links:
        if not (mesh.contains(a) and mesh.contains(b)):
            raise codec.CodecError(
                f"node-topology: badLinks endpoint outside mesh "
                f"{mesh.dims}: {[a.as_list(), b.as_list()]}"
            )
        if b not in mesh.neighbors(a):
            raise codec.CodecError(
                f"node-topology: badLinks pair not ICI-adjacent: "
                f"{[a.as_list(), b.as_list()]}"
            )
    slice_id = obj.get("slice", DEFAULT_SLICE)
    if not isinstance(slice_id, str) or not slice_id:
        raise codec.CodecError(
            f"node-topology: bad slice id {slice_id!r}")
    anno_name = codec._field(obj, "node", "node-topology")
    if anno_name != name:
        raise codec.CodecError(
            f"node-topology annotation names {anno_name!r} but lives "
            f"on {name!r}"
        )
    return {
        "slice": slice_id,
        "mesh": mesh,
        "coords": coords,
        "unhealthy": unhealthy,
        "links": bad_links,
        "shares": shares,
        "healthy_chips": len(coords) - len(unhealthy),
    }


@dataclass
class SliceView:
    """One ICI domain: its mesh geometry plus the data-driven coord->host
    map built from node annotations (host naming is a sim convention, not a
    contract — the annotation's chip coords are the truth).

    ``pending_hosts`` is the checkpoint restore's lazily-parsed host
    map (a compact ``x,y,z=name;...`` blob): a warm restart must not
    pay 40k tuple constructions up front for a map most recoveries
    never walk — ``ClusterState._hosts_locked`` expands it on first
    touch. ``hosts_blob`` caches the serialized form for checkpoint
    captures, invalidated on any host-map mutation."""

    mesh: MeshSpec
    host_by_coord: dict[TopologyCoord, str] = field(default_factory=dict)
    pending_hosts: Optional[str] = None
    hosts_blob: Optional[str] = None


class ClusterState:
    """Thread-safe ledger: per-slice node views + per-chip share occupancy.

    The extender serves concurrent webhook calls; all mutation goes through
    this object's lock (SURVEY.md §9.3: reservations must be linearizable
    under concurrent filter calls — the gang layer in M7 builds on this).

    A cluster holds one or more ICI slices (SURVEY.md §3 "distributed
    communication backend": ICI intra-slice, DCN inter-slice). Chip coords
    are slice-local, so every coord-set accessor takes a slice id; the
    no-argument forms serve the common single-slice cluster and raise on
    ambiguity rather than silently mixing coordinate spaces.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._nodes: dict[str, NodeView] = {}
        self._slices: dict[str, SliceView] = {}
        self._allocs: dict[str, AllocResult] = {}  # pod key -> commitment
        # frozen coord->host snapshots handed to hot-path callers; rebuilt
        # lazily after any host-map mutation (annotations rarely change)
        self._hosts_cache: dict[str, dict[TopologyCoord, str]] = {}
        # ledger epoch: bumped by EVERY mutation (node upsert, commit,
        # release — rebuild_from_pods goes through commit). The epoch-
        # cached scheduling snapshot (sched/snapshot.py) keys its
        # validity on this, so a missed bump here would serve stale
        # placements — treat any new mutation path as epoch-bumping.
        self._epoch = 0
        # snapshot delta sink (sched/snapshot.py SnapshotCache, wired
        # by the owning GangManager): every epoch bump pairs with a
        # _note_delta so the cache can advance O(Δ) instead of
        # rebuilding. A bump without a note degrades to a full rebuild
        # (log gap), never to a stale cache.
        self._delta_sink = None
        # durable-state journal (sched/journal.py StateJournal, wired by
        # the Extender when journal_enabled): mutation seams enqueue one
        # typed WAL record each — enqueue only, the file write happens
        # on the journal's drain thread, so this lock never blocks on
        # disk. None (the default) journals nothing.
        self._journal = None
        # cached sorted node-name tuple, invalidated when the node SET
        # changes (a new node registers / a checkpoint restore) — NOT on
        # occupancy or health churn. node_names() runs per batch cycle;
        # a fresh sorted list per call was O(fleet) per cycle at 10k
        # nodes (ROADMAP O(fleet) item).
        self._names_cache: Optional[tuple[str, ...]] = None
        # LAZY checkpoint restore (sched/journal.py warm recovery):
        # nodes not yet materialized live as positions into the open
        # checkpoint file — name -> (abs offset, length, line crc,
        # slice id, payload crc, payload len). _view_locked()
        # materializes a view on first touch (an os.pread of one line
        # plus one small parse), so restart-to-serving pays O(Δ), not
        # O(fleet); a background warmer drains the rest off the hot
        # path. _lazy_allocs indexes restored allocations by node so a
        # materialized view recovers its occupancy.
        self._lazy_index: dict[str, tuple] = {}
        self._lazy_fd: Optional[int] = None
        self._lazy_allocs: dict[str, list[AllocResult]] = {}
        # set by retire(): the owning process is done with this ledger
        # (sim crash/stop) — the background warmer must stop instead of
        # materializing an orphan's fleet against the live one's CPU
        self._retired = False
        # Per-slice occupied-coord sets, maintained INCREMENTALLY at
        # the same seams the snapshot deltas fire from (commit /
        # release / health-only re-annotation / structural upsert), so
        # a forced structural rebuild stops walking every view of the
        # slice (ROADMAP O(fleet) item; at 10k nodes the walk was the
        # rebuild's dominant term). A slice absent from the dict is
        # UNSEEDED: the first occupied_coords() call derives the set
        # with the full walk (materializing any lazy nodes of the
        # slice, which pins the invariant that later lazy
        # materializations only happen in unseeded slices) and seeds
        # it. The audit sentinel deliberately bypasses this cache
        # (walk_occupied_coords) so a seam that forgot BOTH its delta
        # and its incremental update still cannot hide from the audit.
        self._occ_cache: dict[str, set[TopologyCoord]] = {}
        # The REMAINING per-slice structural walks, given the same
        # incremental treatment (ISSUE 14 satellite; ROADMAP O(fleet)
        # item): unhealthy coord sets, broken-link report counts, and
        # the (used, total) share integers. Same seeding/unseeded
        # contract as _occ_cache, same seams (structural upsert,
        # health-only re-annotation, commit, release), and the audit
        # sentinel again re-derives via the walk_* variants so these
        # caches can never hide a missed seam. _broken_cache counts
        # REPORTING VIEWS per canonical link (both endpoint hosts may
        # report one link; the set view is the keys with count > 0).
        self._unhealthy_cache: dict[str, set[TopologyCoord]] = {}
        self._broken_cache: dict[str, dict[Link, int]] = {}
        self._share_cache: dict[str, list[int]] = {}  # sid -> [used, total]
        # Bulk cold-start ingestion (ISSUE 15 tentpole): nodes ingested
        # through ingest_nodes() live here UNDECODED — name ->
        # (topology payload, full annotation dict, slice id) — until
        # first touch materializes a NodeView (_view_locked), the same
        # lazy contract the checkpoint restore's _lazy_index keeps. The
        # probe already ran every validation the full decode enforces
        # and extracted the host map + health/link aggregates, so a
        # materialization failure is pathological (and degrades that
        # one node to 'unknown', like a CRC-failing checkpoint line).
        self._lazy_payloads: dict[str, tuple[str, dict[str, str], str]] = {}
        # decode-avoidance counters: a batch item whose payload
        # matches the retained one by signature (a webhook re-send of
        # an unchanged fleet) is a HIT — answered without any parse; a
        # fresh probe (a parse) is a miss. Every payload embeds its
        # own node name, so cross-NODE payloads are never identical —
        # the win is per-node re-send suppression, and the hit rate
        # reads ~1.0 in steady state / 0.0 on a cold start.
        self._decode_hits = 0
        self._decode_misses = 0
        # ingest counters (the /statusz "ingest" section + the
        # tpukube_ingest_* series)
        self.ingest_nodes_total = 0
        self.ingest_batches = 0
        self._ingest_seconds: deque[float] = deque(maxlen=64)
        self.ingest_seconds_total = 0.0
        self._warming = False
        # Generation-based incremental resync (ISSUE 15 tentpole): a
        # monotonically increasing generation stamped on every ALLOC
        # mutation seam (commit/release — exactly the set
        # ``allocations()`` serves), plus a bounded per-generation
        # change log ``allocs_since`` answers adds/removes from. The
        # log is None until set_generation_log() sizes it (the Extender
        # wires config.generation_log_capacity; 0 keeps it off) —
        # disabled, allocs_since answers None and consumers keep the
        # legacy full read. A cursor the log cannot cover (gap,
        # restart, overflow) gets a FULL answer — never a stale one.
        self._generation = 0
        self._incarnation = f"{os.getpid():x}.{next(_INCARNATIONS):x}"
        self._gen_log: Optional[deque] = None
        # Cordoned node names (fleet elasticity, ISSUE 19): excluded
        # from every placement sweep while their live allocations keep
        # serving — the drain choreography's first act. Rides the WAL
        # ("cordon" records) and the checkpoint head ("cordoned", only
        # when non-empty, so journal bytes stay byte-identical with
        # the drain plane off). No incremental coord cache: cordons
        # are rare, and the snapshot derives coords on demand
        # (cordoned_coords) for build and audit alike.
        self._cordoned: set[str] = set()
        # decommission counters (the /statusz "ingest" section's twin)
        self.removed_nodes_total = 0
        self.removed_batches = 0

    def set_delta_sink(self, sink) -> None:
        """Attach the snapshot cache's delta log (None detaches)."""
        with self._lock:
            self._delta_sink = sink

    def set_journal(self, journal) -> None:
        """Attach the durable-state journal (None detaches — recovery
        replays with the journal detached so replayed mutations are not
        re-recorded)."""
        with self._lock:
            self._journal = journal

    def _note_journal_locked(self, kind: str, data: dict) -> None:
        """Enqueue one WAL record for the mutation just applied
        (callers hold ``self._lock``; non-blocking — see StateJournal).
        ``data`` must be freshly built and never mutated afterwards:
        the journal serializes it on its drain thread."""
        journal = self._journal
        if journal is not None:
            journal.note(kind, data)

    def _note_delta_locked(self, full: bool = False,
                    slice_id: Optional[str] = None,
                    occupied_add: tuple = (), occupied_remove: tuple = (),
                    used_shares_delta: int = 0,
                    unhealthy_add: tuple = (), unhealthy_remove: tuple = (),
                    total_shares_delta: int = 0, why: str = "") -> None:
        """Record the bump just taken (callers hold ``self._lock`` and
        call this right after ``self._epoch += 1``). Import is lazy and
        one-directional: snapshot.py never imports state."""
        sink = self._delta_sink
        if sink is None:
            return
        from tpukube.sched.snapshot import SnapshotDelta

        sink.note(SnapshotDelta(
            kind="ledger", epoch=self._epoch, full=full,
            slice_id=slice_id, occupied_add=occupied_add,
            occupied_remove=occupied_remove,
            used_shares_delta=used_shares_delta,
            unhealthy_add=unhealthy_add,
            unhealthy_remove=unhealthy_remove,
            total_shares_delta=total_shares_delta, why=why,
        ))

    def epoch(self) -> int:
        """Monotonic mutation counter (the snapshot cache's key half)."""
        with self._lock:
            return self._epoch

    # -- generation-based incremental resync (ISSUE 15) ----------------------
    def set_generation_log(self, capacity: int) -> None:
        """Size (and enable) the per-generation alloc change log; 0
        disables it — ``allocs_since`` then answers None and consumers
        keep the legacy full read. The capacity must exceed the deepest
        alloc churn between two consumer reads (a churn wave's commits
        plus its releases) or steady-state resyncs degrade to full
        reads (counted, never wrong)."""
        with self._lock:
            self._gen_log = deque(maxlen=capacity) if capacity > 0 \
                else None

    def _note_gen_locked(self, kind: str, alloc=None,
                         pod_key: Optional[str] = None) -> None:
        """Stamp one alloc mutation (callers hold ``self._lock`` and
        call this right where the ``_allocs`` map changed)."""
        self._generation += 1
        gen_log = self._gen_log
        if gen_log is not None:
            gen_log.append((
                self._generation, kind,
                alloc if kind == "add" else pod_key,
            ))

    def generation(self):
        """The opaque resync cursor: (ledger incarnation, generation).
        Feed it back into ``allocs_since`` to read only what changed."""
        with self._lock:
            return (self._incarnation, self._generation)

    def allocs_since(self, cursor) -> Optional[dict]:
        """The alloc changes since ``cursor`` (a prior answer's
        ``cursor``, or None to bootstrap). None when the log is
        disabled (legacy full-read consumers); otherwise a dict:

          * ``{"cursor": C, "adds": [AllocResult...], "removes":
            [pod_key...], "bytes": n}`` — the incremental answer;
            apply removes, then adds, to a mirror of the ledger.
          * ``{"cursor": C, "full": [AllocResult...], "bytes": n}`` —
            bootstrap, wrong incarnation (a restart), or a log gap
            (overflow): the full ledger. A gap ALWAYS degrades to this
            — never to a stale or partial answer.

        ``bytes`` is the wire-shape size of the answer (encoded alloc
        lengths) — what a remote consumer would actually move; the
        tpukube_resync_bytes_total feed."""
        with self._lock:
            gen_log = self._gen_log
            if gen_log is None:
                return None
            cur = (self._incarnation, self._generation)
            gen: Optional[int] = None
            if cursor is not None:
                try:
                    inc, gen = cursor[0], int(cursor[1])
                except (TypeError, ValueError, IndexError):
                    gen = None
                else:
                    if inc != self._incarnation or gen > self._generation:
                        gen = None  # another incarnation's cursor
            if gen is None or (
                gen < self._generation
                and (not gen_log or gen_log[0][0] > gen + 1)
            ):
                # bootstrap or gap: the log cannot cover (gen, now]
                allocs = list(self._allocs.values())
                return {"cursor": cur, "full": allocs,
                        "bytes": _alloc_bytes(allocs)}
            # net effect per pod key, in generation order (an add after
            # a remove of the same key is an add, and vice versa)
            merged: dict[str, tuple[str, Optional[AllocResult]]] = {}
            for g, kind, payload in gen_log:
                if g <= gen:
                    continue
                if kind == "add":
                    merged[payload.pod_key] = ("add", payload)
                else:
                    merged[payload] = ("remove", None)
            adds = [a for kind, a in merged.values() if kind == "add"]
            removes = [k for k, (kind, _) in merged.items()
                       if kind == "remove"]
            return {
                "cursor": cur, "adds": adds, "removes": removes,
                "bytes": _alloc_bytes(adds) + sum(
                    len(k) for k in removes),
            }

    # -- lazy materialization (checkpoint warm restore) ---------------------
    def _view_locked(self, name: str) -> Optional[NodeView]:
        """The node's view, materializing it from the open checkpoint
        file on first touch (callers hold ``self._lock``). None for
        unknown nodes OR for a node whose checkpoint line fails its
        CRC — the latter degrades that one node to 'unknown' (its next
        re-annotation re-registers it) instead of crashing recovery."""
        view = self._nodes.get(name)
        if view is not None:
            return view
        lazy = self._lazy_payloads.pop(name, None)
        if lazy is not None:
            # bulk-ingested node (ISSUE 15): decode the retained
            # annotations on first touch — the probe already ran every
            # validation, so failure here is pathological
            return self._materialize_payload_locked(name, *lazy)
        entry = self._lazy_index.pop(name, None)
        if entry is None:
            return None
        off, length, crc, sid, _pcrc, _plen = entry
        try:
            raw = os.pread(self._lazy_fd, length, off)
        except OSError as e:
            log.error("lazy node %s: checkpoint read failed: %s",
                      name, e)
            self._names_cache = None  # the node SET just shrank
            self._drop_lazy_fd_locked()
            return None
        if zlib.crc32(raw) != crc:
            log.error("lazy node %s: checkpoint line fails its CRC; "
                      "treating the node as unknown until it "
                      "re-annotates", name)
            self._names_cache = None  # the node SET just shrank
            self._drop_lazy_fd_locked()
            return None
        doc = json.loads(raw.decode("utf-8"))
        if "anno" in doc:
            # a checkpoint line captured from a still-lazy bulk-ingest
            # node carries the RAW annotations (never decoded by the
            # capturing process) — decode on touch, same as the
            # in-memory lazy store it round-tripped from
            self._drop_lazy_fd_locked()
            return self._materialize_payload_locked(
                name,
                (doc["anno"] or {}).get(codec.ANNO_NODE_TOPOLOGY, ""),
                dict(doc["anno"] or {}), doc["slice"],
            )
        mesh = self._slices[sid].mesh
        view = _view_from_doc(doc, mesh)
        for alloc in self._lazy_allocs.pop(name, ()):
            # re-apply the restored occupancy exactly as the eager
            # restore would; materialization changes NOTHING observable
            # (the same content was reachable through the lazy doc), so
            # no epoch moves — the seeded snapshot stays valid
            view.add_ids(alloc.device_ids)  # tpukube: allow(epoch-discipline) materialization promotes equivalent state; nothing observable changes, so the snapshot must NOT invalidate
        self._nodes[name] = view  # tpukube: allow(epoch-discipline) see above — cache promotion, not a mutation
        self._drop_lazy_fd_locked()
        return view

    def _drop_lazy_fd_locked(self) -> None:
        """Close the checkpoint fd once nothing lazy remains."""
        if not self._lazy_index and self._lazy_fd is not None:
            try:
                os.close(self._lazy_fd)
            except OSError:
                pass
            self._lazy_fd = None

    def _materialize_payload_locked(
        self, name: str, payload: str, annotations: dict[str, str],
        sid: str,
    ) -> Optional[NodeView]:
        """Materialize one bulk-ingested lazy node from its retained
        annotations (callers hold ``self._lock``; the entry is already
        popped). This is the deferred half of an ingest the probe
        already validated, so a failure here is pathological and
        degrades the node to 'unknown' (its next re-annotation
        re-registers it) exactly like a CRC-failing checkpoint line."""
        del sid
        try:
            info, _mesh = codec.decode_node_topology(payload)
        except codec.CodecError as e:
            log.error("lazy node %s: retained payload fails its full "
                      "decode (%s); treating the node as unknown until "
                      "it re-annotates", name, e)
            self._names_cache = None  # the node SET just shrank
            return None
        if info.name != name:
            log.error("lazy node %s: retained payload names %r; "
                      "treating the node as unknown", name, info.name)
            self._names_cache = None
            return None
        info.annotations = dict(annotations)
        summary = None
        raw_summary = annotations.get(codec.ANNO_HEALTH_SUMMARY)
        if raw_summary:
            try:
                summary = codec.decode_health_summary(raw_summary)
            except codec.CodecError as e:
                # same tolerance as the eager upsert path: a malformed
                # summary never rejects the topology
                log.warning("node %s: undecodable health summary: %s",
                            name, e)
        view = NodeView(info=info, raw_payload=payload,
                        health_summary=summary)
        for alloc in self._lazy_allocs.pop(name, ()):
            # checkpoint-restored occupancy re-applies exactly as the
            # eager restore would; materialization changes NOTHING
            # observable, so no epoch moves (see _view_locked)
            view.add_ids(alloc.device_ids)  # tpukube: allow(epoch-discipline) materialization promotes equivalent state; nothing observable changes, so the snapshot must NOT invalidate
        self._nodes[name] = view  # tpukube: allow(epoch-discipline) see above — cache promotion, not a mutation
        return view

    def _materialize_slice_locked(self, slice_id: Optional[str]) -> None:
        """Materialize every lazy node of one slice (None = all) ahead
        of a whole-slice scan (occupied_coords and friends)."""
        if not self._lazy_index and not self._lazy_payloads:
            return
        for name in [
            n for n, e in self._lazy_index.items()
            if slice_id is None or e[3] == slice_id
        ]:
            self._view_locked(name)
        for name in [
            n for n, e in self._lazy_payloads.items()
            if slice_id is None or e[2] == slice_id
        ]:
            self._view_locked(name)

    def warm_pending(self, limit: int = 512) -> int:
        """Materialize up to ``limit`` lazy nodes; returns how many
        remain. The recovery's background warmer drains the fleet in
        batches so the first full-fleet scan (a structural snapshot
        rebuild, a metrics scrape) finds the work already done —
        batched so the warmer never holds the ledger lock long."""
        with self._lock:
            if self._retired:
                return 0
            batch = list(self._lazy_index)[:limit]
            if len(batch) < limit:
                batch += list(self._lazy_payloads)[:limit - len(batch)]
            for name in batch:
                self._view_locked(name)
            return len(self._lazy_index) + len(self._lazy_payloads)

    def retire(self) -> None:
        """Stop background warming for good (the owner crashed or shut
        down; an orphaned ledger must not keep materializing)."""
        with self._lock:
            self._retired = True

    def lazy_fd_dup(self) -> Optional[int]:
        """A dup of the open checkpoint fd while lazy nodes remain
        (None otherwise) — checkpoint captures hand it to the journal's
        drain thread so ``("ref", ...)`` entries stay readable even if
        the last lazy node materializes (closing the original) before
        the write lands. The caller owns the dup."""
        with self._lock:
            if self._lazy_fd is None or not self._lazy_index:
                return None
            return os.dup(self._lazy_fd)

    def payload_matches(self, name: str, payload: str) -> bool:
        """True when the node's stored topology payload equals
        ``payload`` — WITHOUT materializing a lazy node (recovery's
        reconcile compares every node; only divergent ones may cost
        anything). Lazy entries compare by (crc32, length)."""
        with self._lock:
            return self._payload_matches_locked(name, payload)

    def _payload_matches_locked(self, name: str, payload: str) -> bool:
        view = self._nodes.get(name)
        if view is not None:
            return view.raw_payload == payload
        lazy = self._lazy_payloads.get(name)
        if lazy is not None:
            return lazy[0] == payload
        entry = self._lazy_index.get(name)
        if entry is None:
            return False
        raw = payload.encode("utf-8")
        return entry[4] == zlib.crc32(raw) and entry[5] == len(raw)

    def nodes_matching_payloads(
        self, payloads: dict[str, str]
    ) -> set[str]:
        """The names whose stored payload equals the given one, in ONE
        lock round-trip (the recovery reconcile compares the whole
        fleet; 10k separate lock acquisitions were a measurable slice
        of restart-to-serving). Lazy nodes stay lazy."""
        with self._lock:
            nodes = self._nodes
            lazy = self._lazy_index
            crc32 = zlib.crc32
            out: set[str] = set()
            lazy_payloads = self._lazy_payloads
            for name, payload in payloads.items():
                view = nodes.get(name)
                if view is not None:
                    if view.raw_payload == payload:
                        out.add(name)
                    continue
                entry2 = lazy_payloads.get(name)
                if entry2 is not None:
                    if entry2[0] == payload:
                        out.add(name)
                    continue
                entry = lazy.get(name)
                if entry is None:
                    continue
                raw = payload.encode("utf-8")
                if entry[4] == crc32(raw) and entry[5] == len(raw):
                    out.add(name)
            return out

    def _hosts_locked(self, sl: SliceView) -> dict[TopologyCoord, str]:
        """The slice's coord->host map, expanding a checkpoint
        restore's compact pending blob on first touch."""
        if sl.pending_hosts is not None:
            blob, sl.pending_hosts = sl.pending_hosts, None
            hosts = sl.host_by_coord
            for part in blob.split(";"):
                if not part:
                    continue
                coord, _, host = part.partition("=")
                x, y, z = coord.split(",")
                hosts[TopologyCoord(int(x), int(y), int(z))] = host
        return sl.host_by_coord

    # -- node ingestion ----------------------------------------------------
    def upsert_node(self, name: str, annotations: dict[str, str]) -> bool:
        """Decode and store a node's topology annotation. Returns False when
        the node carries no tpukube annotation (not ours to manage)."""
        payload = annotations.get(codec.ANNO_NODE_TOPOLOGY)
        if payload is None:
            return False
        if self.payload_matches(name, payload):
            # unchanged annotation: keep the stored view (a LAZY node
            # compares by crc+length and stays unmaterialized — the
            # hot webhook resend path must not force the fleet in)
            return True
        decoded = codec.node_from_annotations(name, annotations)
        if decoded is None:
            return False
        info, mesh = decoded
        summary = None
        raw_summary = annotations.get(codec.ANNO_HEALTH_SUMMARY)
        if raw_summary:
            try:
                summary = codec.decode_health_summary(raw_summary)
            except codec.CodecError as e:
                # a malformed summary must not reject the topology —
                # the rollup simply falls back to chip health
                log.warning("node %s: undecodable health summary: %s",
                            name, e)
        with self._lock:
            prev = self._view_locked(name)
            if (prev is not None
                    and prev.info.slice_id == info.slice_id
                    and _health_only_change(prev.info, info)):
                # HEALTH-ONLY re-annotation (the health watch's steady
                # churn shape): same chips, same links, same sharing
                # mode — only per-chip health flipped. Emit an
                # O(chips-per-node) snapshot delta instead of the
                # full-rebuild marker a changed payload used to cost
                # (ROADMAP O(fleet) item: at 40k chips a health flap
                # forced a ~50ms rebuild; WAL replay of health churn
                # degenerated to full rebuilds the same way). The
                # coord->host map is untouched (coords identical), so
                # the claim-validation walk and host-map rewrite of the
                # structural path are skipped too.
                self._apply_health_only_locked(
                    name, prev, info, payload, summary, annotations)
                return True
            sl = self._slices.get(info.slice_id)
            if sl is None:
                sl = self._slices[info.slice_id] = SliceView(mesh=mesh)
                # the slice set feeds snapshot.slice_ids(): bump at the
                # seam itself, not only at the end of the upsert — the
                # validation raises below must not leave a registered
                # slice invisible to the epoch cache (found by
                # tpukube-lint's epoch-discipline pass)
                self._epoch += 1
                # a new slice is structural: the delta path cannot
                # patch a slice the base snapshot never held
                self._note_delta_locked(full=True,
                                 why=f"slice {info.slice_id} registered")
            elif sl.mesh != mesh:
                raise StateError(
                    f"node {name} reports mesh {mesh.dims} for slice "
                    f"{info.slice_id}, which has {sl.mesh.dims} — nodes of "
                    f"one slice must agree on its geometry"
                )
            prev = self._nodes.get(name)
            if prev is not None and prev.info.slice_id != info.slice_id:
                # tpukube: allow(seam-triple) a slice registered by an upsert that then fails validation holds no nodes; the WAL only records successful upserts, so a restart simply never sees the empty slice
                raise StateError(
                    f"node {name} moved from slice {prev.info.slice_id} "
                    f"to {info.slice_id} — drop and re-add the node"
                )
            if (
                prev is not None
                and prev.used_ids
                and prev.info.shares_per_chip != info.shares_per_chip
            ):
                # a sharing-mode switch under live allocations cannot be
                # accounted (committed ids carry the OLD mode's weights;
                # mixing modes double-books chips) — drain the node first
                # tpukube: allow(seam-triple) failed-validation raise: the registered-but-empty slice is deliberately not journaled (records land on success only)
                raise StateError(
                    f"node {name} changed shares_per_chip "
                    f"{prev.info.shares_per_chip} -> {info.shares_per_chip} "
                    f"with {len(prev.used_ids)} live allocations — drain "
                    f"the node before switching sharing mode"
                )
            # validate EVERY claim before mutating anything: a partial
            # apply would leave phantom claims with no owner on error
            hosts = self._hosts_locked(sl)
            for chip in info.chips:
                claimed = hosts.get(chip.coord)
                if claimed is not None and claimed != name:
                    # tpukube: allow(seam-triple) failed-validation raise: the registered-but-empty slice is deliberately not journaled (records land on success only)
                    raise StateError(
                        f"nodes {claimed} and {name} both claim chip "
                        f"{tuple(chip.coord)} in slice {info.slice_id}"
                    )
            if prev is not None:
                for chip in prev.info.chips:
                    if hosts.get(chip.coord) == name:
                        del hosts[chip.coord]
            for chip in info.chips:
                hosts[chip.coord] = name
            sl.hosts_blob = None
            self._hosts_cache.pop(info.slice_id, None)
            view = NodeView(info=info, raw_payload=payload,
                            health_summary=summary)
            if prev is not None:
                view.used_ids = prev.used_ids
                view.share_counts = prev.share_counts
                view.id_weights = prev.id_weights
            else:
                # the node SET changed: the cached name tuple is stale
                self._names_cache = None
            self._nodes[name] = view
            # incremental occupied maintenance for the structural path:
            # ONE node's old contribution leaves, its new one enters —
            # O(chips-per-node), so the full-rebuild the marker below
            # forces stops walking every OTHER view of the slice
            occ_old = tuple(
                c.coord for c in prev.info.chips
                if c.health is not Health.HEALTHY
                or prev.used_share_count(c.index) > 0
            ) if prev is not None else ()
            occ_new = tuple(
                c.coord for c in info.chips
                if c.health is not Health.HEALTHY
                or view.used_share_count(c.index) > 0
            )
            self._occ_apply_locked(info.slice_id, add=occ_new,
                                   remove=occ_old)
            # ... and the same one-node-out/one-node-in transition for
            # the unhealthy/broken/share caches (ISSUE 14 satellite)
            used_old, total_old = (self._view_share_counts(prev)
                                   if prev is not None else (0, 0))
            used_new, total_new = self._view_share_counts(view)
            self._aux_apply_locked(
                info.slice_id,
                unhealthy_add=tuple(
                    c.coord for c in info.chips
                    if c.health is not Health.HEALTHY
                ),
                unhealthy_remove=tuple(
                    c.coord for c in prev.info.chips
                    if c.health is not Health.HEALTHY
                ) if prev is not None else (),
                broken_add=tuple(set(info.bad_links)),
                broken_remove=(tuple(set(prev.info.bad_links))
                               if prev is not None else ()),
                used_delta=used_new - used_old,
                total_delta=total_new - total_old,
            )
            self._epoch += 1
            # a STRUCTURALLY changed node payload may move links,
            # topology, or sharing mode — all structural for the
            # snapshot (they shift broken sets and the share totals the
            # delta math assumes constant): full-rebuild marker. The
            # unchanged-payload early return above keeps the hot
            # webhook resend path bump- and delta-free, and the
            # health-only path above keeps health churn O(chips/node).
            self._note_delta_locked(full=True, why=f"node {name} re-annotated")
            self._note_journal_locked(
                "node", {"n": name, "anno": dict(annotations)})
        return True

    def _apply_health_only_locked(
        self, name: str, prev: NodeView, info: NodeInfo, payload: str,
        summary: Optional[dict], annotations: dict[str, str],
    ) -> None:
        """Apply a health-only re-annotation (see upsert_node): swap the
        node view and emit the per-chip transition delta — occupied and
        unhealthy set moves plus the healthy-share capacity change the
        slice's utilization integers carry. Callers hold ``self._lock``
        and have verified ``_health_only_change``."""
        n = prev.shares_per_chip
        occupied_add: list[TopologyCoord] = []
        occupied_remove: list[TopologyCoord] = []
        unhealthy_add: list[TopologyCoord] = []
        unhealthy_remove: list[TopologyCoord] = []
        used_d = total_d = 0
        for old_chip, new_chip in zip(prev.info.chips, info.chips):
            if old_chip.health is new_chip.health:
                continue
            # counted shares on this chip (slice_share_counts caps at n)
            cnt = min(n, prev.used_share_count(new_chip.index))
            if new_chip.health is not Health.HEALTHY:
                unhealthy_add.append(new_chip.coord)
                total_d -= n
                used_d -= cnt
                if cnt == 0:
                    # a free chip turning sick ENTERS occupied (health
                    # holds it); a chip with live shares was there already
                    occupied_add.append(new_chip.coord)
            else:
                unhealthy_remove.append(new_chip.coord)
                total_d += n
                used_d += cnt
                if cnt == 0:
                    occupied_remove.append(new_chip.coord)
        view = NodeView(info=info, raw_payload=payload,
                        health_summary=summary)
        view.used_ids = prev.used_ids
        view.share_counts = prev.share_counts
        view.id_weights = prev.id_weights
        self._nodes[name] = view
        self._occ_apply_locked(info.slice_id, add=tuple(occupied_add),
                               remove=tuple(occupied_remove))
        self._aux_apply_locked(
            info.slice_id,
            unhealthy_add=tuple(unhealthy_add),
            unhealthy_remove=tuple(unhealthy_remove),
            # links are untouched on a health-only change by definition
            used_delta=used_d, total_delta=total_d,
        )
        self._epoch += 1
        self._note_delta_locked(
            slice_id=info.slice_id,
            occupied_add=tuple(occupied_add),
            occupied_remove=tuple(occupied_remove),
            used_shares_delta=used_d,
            unhealthy_add=tuple(unhealthy_add),
            unhealthy_remove=tuple(unhealthy_remove),
            total_shares_delta=total_d,
            why=f"node {name} health re-annotated",
        )
        self._note_journal_locked(
            "node", {"n": name, "anno": dict(annotations)})

    # -- bulk cold-start ingestion (ISSUE 15 tentpole) -----------------------
    def ingest_nodes(self, items: list[dict]) -> list:
        """Fleet-scale node ingest fast path. Each item is ``{"name":
        ..., "annotations": {...}}``; the result list matches the
        per-item ``upsert_node`` decision responses positionally
        (``{"ours": bool}`` or ``{"error": str}``).

        Semantics match per-item upserts — the parity suite proves the
        resulting ledger/host/occupancy state identical — but the cost
        model is the cold start's: payloads are PROBED (validated +
        host-mapped) without building NodeView objects, the decoded
        views materialize lazily on first touch exactly like the
        checkpoint restore's, the per-slice incremental coord/share
        caches are seeded from the probe aggregates (so the first
        snapshot rebuild is O(slices), not O(fleet)), and the
        epoch/delta/journal seam fires ONCE per batch instead of per
        node. Items naming an already-known node with a CHANGED payload
        are routed through the legacy per-node path (its health-only
        delta and occupancy carry-over semantics own that shape)."""
        t0 = time.perf_counter()
        results: list = [None] * len(items)
        slow: list[int] = []
        with self._lock:
            # phase 1 — probe + validate: reads only, nothing mutated,
            # so a bad item errors without a partial apply
            staged: list[tuple] = []  # (pos, name, payload, annos, probe)
            mesh_memo: dict = {}  # one mesh decode per distinct fragment
            new_slices: dict[str, MeshSpec] = {}
            staged_hosts: dict[str, dict[TopologyCoord, str]] = {}
            agg: dict[str, dict] = {}  # sid -> batch aggregates
            # hot-loop locals (this loop runs per node of the fleet)
            nodes_get = self._nodes.get
            lazyp_get = self._lazy_payloads.get
            lazy_index = self._lazy_index
            slices_get = self._slices.get
            anno_key = codec.ANNO_NODE_TOPOLOGY
            # per-sid (live_hosts, live_get, batch_hosts, agg entry):
            # resolved once per slice, not once per node
            slice_ctx: dict[str, tuple] = {}
            staged_payloads: dict[str, str] = {}  # name staged earlier
            for pos, item in enumerate(items):
                name = item["name"]
                annotations = dict(item.get("annotations") or {})
                payload = annotations.get(anno_key)
                if payload is None:
                    results[pos] = {"ours": False}
                    continue
                view = nodes_get(name)
                if view is not None:
                    if view.raw_payload == payload:
                        self._decode_hits += 1
                        results[pos] = {"ours": True}
                    else:
                        slow.append(pos)
                    continue
                lazy = lazyp_get(name)
                if lazy is not None:
                    if lazy[0] == payload:
                        self._decode_hits += 1
                        results[pos] = {"ours": True}
                    else:
                        slow.append(pos)
                    continue
                if name in lazy_index:
                    if self._payload_matches_locked(name, payload):
                        self._decode_hits += 1
                        results[pos] = {"ours": True}
                    else:
                        slow.append(pos)
                    continue
                earlier = staged_payloads.get(name)
                if earlier is not None:
                    # the SAME node twice in one batch: the per-node
                    # path's second upsert answers unchanged-payload
                    # True / runs the re-annotation path — match it
                    # (the name-string identity trick below only
                    # covers claims within ONE item)
                    if earlier == payload:
                        self._decode_hits += 1
                        results[pos] = {"ours": True}
                    else:
                        slow.append(pos)
                    continue
                self._decode_misses += 1
                try:
                    probe = _probe_node_payload(name, payload,
                                                mesh_memo)
                except codec.CodecError as e:
                    results[pos] = {"error": str(e)}
                    continue
                sid = probe["slice"]
                mesh = probe["mesh"]
                ctx = slice_ctx.get(sid)
                if ctx is None:
                    sl = slices_get(sid)
                    live_hosts = (self._hosts_locked(sl)
                                  if sl is not None else {})
                    a = agg[sid] = {"unhealthy": set(), "links": {},
                                    "total": 0}
                    batch_hosts = staged_hosts[sid] = {}
                    ctx = slice_ctx[sid] = (
                        sl.mesh if sl is not None else None,
                        live_hosts, live_hosts.get,
                        batch_hosts, batch_hosts.setdefault, a,
                    )
                (live_mesh, live_hosts, live_get,
                 batch_hosts, bh_setdefault, a) = ctx
                have_mesh = (live_mesh if live_mesh is not None
                             else new_slices.get(sid))
                # identity first: the memo hands every node of a
                # homogeneous fleet the SAME MeshSpec object, so the
                # dataclass __eq__ runs only on genuine disagreement
                if (have_mesh is not None and have_mesh is not mesh
                        and have_mesh != mesh):
                    results[pos] = {"error": (
                        f"node {name} reports mesh {mesh.dims} for "
                        f"slice {sid}, which has {have_mesh.dims} — "
                        f"nodes of one slice must agree on its geometry"
                    )}
                    continue
                # validate-and-stage in ONE pass (this loop runs per
                # chip of the whole fleet): setdefault stages the claim
                # unless someone staged it first; a conflict unwinds
                # this node's own staged claims (rare) and errors with
                # the per-node path's message. An empty live map (the
                # cold start) skips its per-coord probe entirely.
                claimed_by = None
                if live_hosts:
                    for coord in probe["coords"]:
                        claimed_by = live_get(coord)
                        if claimed_by is None:
                            owner = bh_setdefault(coord, name)
                            if owner is name:
                                continue
                            claimed_by = owner
                        break
                else:
                    for coord in probe["coords"]:
                        owner = bh_setdefault(coord, name)
                        if owner is not name:
                            claimed_by = owner
                            break
                if claimed_by is not None:
                    results[pos] = {"error": (
                        f"nodes {claimed_by} and {name} both claim "
                        f"chip {tuple(coord)} in slice {sid}"
                    )}
                    for coord in probe["coords"]:
                        if batch_hosts.get(coord) is name:
                            del batch_hosts[coord]
                    continue
                if have_mesh is None:
                    new_slices[sid] = mesh
                if probe["unhealthy"]:
                    a["unhealthy"].update(probe["unhealthy"])
                if probe["links"]:
                    for link in set(probe["links"]):
                        a["links"][link] = a["links"].get(link, 0) + 1
                a["total"] += probe["shares"] * probe["healthy_chips"]
                staged_payloads[name] = payload
                staged.append((pos, name, payload, annotations, probe))
            # phase 2 — apply: straight-line mutations, no raises, one
            # deferred epoch/delta/journal seam for the whole batch
            if staged:
                for sid, mesh in new_slices.items():
                    self._slices[sid] = SliceView(mesh=mesh)
                for pos, name, payload, annotations, probe in staged:
                    self._lazy_payloads[name] = (
                        payload, annotations, probe["slice"])
                    results[pos] = {"ours": True}
                for sid, batch_hosts in staged_hosts.items():
                    if not batch_hosts:
                        continue
                    sl = self._slices[sid]
                    self._hosts_locked(sl).update(batch_hosts)
                    sl.hosts_blob = None
                    self._hosts_cache.pop(sid, None)
                    a = agg[sid]
                    if sid in new_slices:
                        # a slice born in this batch is COMPLETE
                        # information: seed the incremental caches so
                        # the first reader never pays the O(slice)
                        # walk that would materialize the lazy fleet
                        self._occ_cache[sid] = set(a["unhealthy"])
                        self._unhealthy_cache[sid] = set(a["unhealthy"])
                        self._broken_cache[sid] = dict(a["links"])
                        self._share_cache[sid] = [0, a["total"]]
                    else:
                        # appending NEW nodes to a live slice: advance
                        # already-seeded caches by the batch aggregates
                        # (fresh nodes hold no shares — pure adds)
                        self._occ_apply_locked(
                            sid, add=tuple(a["unhealthy"]))
                        self._aux_apply_locked(
                            sid,
                            unhealthy_add=tuple(a["unhealthy"]),
                            broken_add=tuple(
                                link for link, n in a["links"].items()
                                for _ in range(n)
                            ),
                            total_delta=a["total"],
                        )
                self._names_cache = None
                self._epoch += 1
                self._note_delta_locked(
                    full=True, why=f"bulk ingest ({len(staged)} nodes)")
                # the note itself no-ops without a journal; the ternary
                # only skips building the O(batch) items list, keeping
                # the call UNCONDITIONAL so the seam-triple pass can
                # prove the bump/delta/journal triple on every path
                self._note_journal_locked("nodes", {"items": [
                    [name, annotations]
                    for _, name, _, annotations, _ in staged
                ] if self._journal is not None else []})
                self.ingest_nodes_total += len(staged)
            self.ingest_batches += 1
            dt = time.perf_counter() - t0
            self._ingest_seconds.append(dt)
            self.ingest_seconds_total += dt
        # known-node changed payloads run the legacy per-node path
        # OUTSIDE the batch lock hold (upsert_node re-acquires; the
        # per-node seams own health-only deltas and occupancy carry)
        for pos in slow:
            item = items[pos]
            try:
                results[pos] = {"ours": self.upsert_node(
                    item["name"], dict(item.get("annotations") or {})
                )}
            except (codec.CodecError, StateError) as e:
                results[pos] = {"error": str(e)}
        return results

    def ingest_stats(self) -> dict:
        """The /statusz "ingest" section: batch counters, decode-cache
        hit rate, and the lazy backlog still awaiting materialization."""
        with self._lock:
            decode = self._decode_hits + self._decode_misses
            last = (self._ingest_seconds[-1]
                    if self._ingest_seconds else None)
            return {
                "nodes_total": self.ingest_nodes_total,
                "batches": self.ingest_batches,
                "seconds_total": round(self.ingest_seconds_total, 6),
                "last_batch_s": (round(last, 6)
                                 if last is not None else None),
                "decode_cache_hits": self._decode_hits,
                "decode_cache_misses": self._decode_misses,
                "decode_cache_hit_rate": (
                    round(self._decode_hits / decode, 4)
                    if decode else None
                ),
                "lazy_pending": (len(self._lazy_index)
                                 + len(self._lazy_payloads)),
            }

    def ingest_seconds_snapshot(self) -> list[float]:
        """Copy of the per-batch ingest-wall window (the /metrics
        summary's values_fn)."""
        with self._lock:
            return list(self._ingest_seconds)

    def maybe_start_warmer(self) -> None:
        """Start (at most one) background materializer draining the
        lazy stores in batches — the bulk ingest epilogue's analog of
        the journal recovery's warmer: the steady-state serving path
        should never meet a cold node, without the ingest paying
        O(fleet) decode up front."""
        with self._lock:
            if (self._warming or self._retired
                    or not (self._lazy_index or self._lazy_payloads)):
                return
            self._warming = True

        def run() -> None:
            try:
                # brief head start for the caller's epilogue — warming
                # is strictly background work
                time.sleep(0.05)
                while self.warm_pending(512):
                    pass
            finally:
                with self._lock:
                    self._warming = False

        threading.Thread(target=run, daemon=True,
                         name="tpukube-ingest-warmer").start()

    # -- cordon / decommission (fleet elasticity, ISSUE 19) ------------------
    def cordoned_nodes(self) -> frozenset:
        """The cordoned node-name set as a frozen copy (one lock
        round-trip for per-request membership checks)."""
        with self._lock:
            return frozenset(self._cordoned)

    def is_cordoned(self, name: str) -> bool:
        with self._lock:
            return name in self._cordoned

    def set_cordon(self, names, cordoned: bool = True) -> list[str]:
        """Cordon (or uncordon) known nodes: their chips leave every
        placement sweep while live allocations keep serving. Unknown
        names are ignored (idempotent — WAL replay may re-apply a
        cordon whose nodes were since removed). Returns the names
        whose state actually changed; one epoch/delta/journal seam per
        changed batch, none when nothing changed."""
        with self._lock:
            # decide first, mutate second: the set write and the epoch
            # bump must share every exit path (epoch-discipline proves
            # it on this shape; interleaved add-per-name would leave a
            # statically-escaping maybe-mutated path)
            changed: list[str] = []
            for name in names:
                if (name not in self._nodes
                        and name not in self._lazy_payloads
                        and name not in self._lazy_index):
                    continue
                if (name in self._cordoned) != cordoned:
                    changed.append(name)
            if not changed:
                return changed
            if cordoned:
                self._cordoned.update(changed)
            else:
                self._cordoned.difference_update(changed)
            self._epoch += 1
            # a cordon moves whole nodes in/out of the placement mask —
            # structural for the snapshot (rare by design: one marker
            # per drain act, not per chip)
            self._note_delta_locked(
                full=True,
                why=(f"{'cordon' if cordoned else 'uncordon'} "
                     f"{len(changed)} node(s)"))
            self._note_journal_locked(
                "cordon", {"n": sorted(changed), "c": bool(cordoned)})
            return changed

    def cordoned_coords(self, slice_id: Optional[str] = None):
        """Chip coords of cordoned nodes in one slice — derived on
        demand (cordons are rare and small; no incremental cache to
        keep honest, so the snapshot's normal build and its audit
        sentinel share this one derivation)."""
        with self._lock:
            sid = self._resolve_sid_locked(slice_id)
            out: set[TopologyCoord] = set()
            if sid is None or not self._cordoned:
                return out
            for name in self._cordoned:
                view = self._nodes.get(name)
                if view is not None:
                    node_sid = view.info.slice_id
                else:
                    lazy = self._lazy_payloads.get(name)
                    if lazy is not None:
                        node_sid = lazy[2]
                    else:
                        entry = self._lazy_index.get(name)
                        if entry is None:
                            continue
                        node_sid = entry[3]
                if node_sid != sid:
                    continue
                view = self._view_locked(name)
                if view is not None:
                    out.update(c.coord for c in view.info.chips)
            return out

    def absent_coords(self, slice_id: Optional[str] = None):
        """Chip coords of the slice's geometry with NO live host claim —
        capacity that left (un-ingest, spot churn) or never arrived (a
        recovery rebuilt from a partially-advertised fleet). Without
        this mask a shrunken slice's departed chips would read as FREE
        in every sweep (phantom capacity: a 16-chip reservation
        "fitting" a 12-chip slice). Derived from the coord->host claim
        map, which every ingest/upsert/remove seam already maintains —
        one derivation shared by the snapshot's normal build and its
        audit sentinel (nothing incremental to keep honest), exactly
        the ``cordoned_coords`` contract. The fully-claimed common case
        is an O(1) length check; only a partially-populated slice pays
        the geometry enumeration."""
        with self._lock:
            sid = self._resolve_sid_locked(slice_id)
            if sid is None:
                return set()
            sl = self._slices.get(sid)
            if sl is None:
                return set()
            hosts = self._hosts_locked(sl)
            if len(hosts) >= sl.mesh.num_chips:
                return set()
            return {c for c in sl.mesh.all_coords() if c not in hosts}

    def remove_nodes(self, names) -> dict:
        """Un-ingest: the inverse of ``ingest_nodes``. Phase 1 probes
        (a node with live allocations is SKIPPED loudly — drain it
        first; unknown names are ignored for replay idempotence), phase
        2 drops the views/lazy payloads/lazy index entries, clears the
        host-map claims, retires the per-slice incremental caches (the
        next reader re-seeds with one walk — never a full rebuild
        here), and deletes slices left empty. ONE epoch bump + one
        delta + one journal record per batch, exactly the ingest
        seam's shape. Returns ``{"removed": [...], "skipped": {...},
        "slices_dropped": [...]}``."""
        with self._lock:
            live: set[str] = {a.node_name for a in self._allocs.values()}
            removed: list[str] = []
            skipped: dict[str, str] = {}
            by_slice: dict[str, list[str]] = {}
            for name in names:
                if name in live:
                    skipped[name] = "live allocations"
                    log.error(
                        "remove_nodes: %s still serves live "
                        "allocations — drain it first; skipped", name)
                    continue
                view = self._nodes.get(name)
                if view is not None:
                    sid = view.info.slice_id
                else:
                    lazy = self._lazy_payloads.get(name)
                    if lazy is not None:
                        sid = lazy[2]
                    else:
                        entry = self._lazy_index.get(name)
                        if entry is None:
                            continue  # unknown: replay idempotence
                        sid = entry[3]
                removed.append(name)
                by_slice.setdefault(sid, []).append(name)
            if not removed:
                return {"removed": [], "skipped": skipped,
                        "slices_dropped": []}
            gone = set(removed)
            for name in removed:
                self._nodes.pop(name, None)
                self._lazy_payloads.pop(name, None)
                self._lazy_index.pop(name, None)
                self._lazy_allocs.pop(name, None)
                self._cordoned.discard(name)
            dropped: list[str] = []
            for sid in by_slice:
                sl = self._slices.get(sid)
                if sl is None:
                    continue
                hosts = self._hosts_locked(sl)
                for coord in [c for c, h in hosts.items()
                              if h in gone]:
                    del hosts[coord]
                sl.hosts_blob = None
                self._hosts_cache.pop(sid, None)
                if not hosts:
                    # every claim left with the batch: the slice is
                    # empty — drop it (a future arrival re-registers)
                    dropped.append(sid)
                    del self._slices[sid]
                    self._occ_cache.pop(sid, None)
                    self._unhealthy_cache.pop(sid, None)
                    self._broken_cache.pop(sid, None)
                    self._share_cache.pop(sid, None)
                else:
                    # partial removal: RETIRE the slice's incremental
                    # caches — the departed views' contributions are
                    # unknown without materializing them, so the next
                    # reader re-seeds with one walk
                    self._occ_cache.pop(sid, None)
                    self._unhealthy_cache.pop(sid, None)
                    self._broken_cache.pop(sid, None)
                    self._share_cache.pop(sid, None)
            self._drop_lazy_fd_locked()
            self._names_cache = None
            self.removed_nodes_total += len(removed)
            self.removed_batches += 1
            self._epoch += 1
            self._note_delta_locked(
                full=True, why=f"un-ingest ({len(removed)} nodes)")
            self._note_journal_locked(
                "unnodes", {"n": sorted(removed)})
            return {"removed": removed, "skipped": skipped,
                    "slices_dropped": dropped}

    # -- views -------------------------------------------------------------
    @property
    def mesh(self) -> Optional[MeshSpec]:
        """The sole slice's mesh (single-slice clusters). None before any
        node is known; StateError when several slices exist — callers on a
        multi-slice cluster must name the slice (slice_mesh)."""
        with self._lock:
            if not self._slices:
                return None
            if len(self._slices) > 1:
                raise StateError(
                    f"cluster has {len(self._slices)} slices; use "
                    f"slice_mesh(slice_id)"
                )
            return next(iter(self._slices.values())).mesh

    def slice_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._slices)

    def slice_mesh(self, slice_id: str) -> MeshSpec:
        with self._lock:
            sl = self._slices.get(slice_id)
            if sl is None:
                raise StateError(f"unknown slice {slice_id!r}")
            return sl.mesh

    def host_at(self, slice_id: str, coord: TopologyCoord) -> Optional[str]:
        """Node owning a chip coord within a slice (annotation-derived)."""
        with self._lock:
            sl = self._slices.get(slice_id)
            if sl is None:
                return None
            return self._hosts_locked(sl).get(coord)

    def hosts_by_coord(self, slice_id: str) -> dict[TopologyCoord, str]:
        """Snapshot of a slice's coord->node map — one lock round-trip for
        callers that look up many coords (the per-node gang hot path).
        The returned dict is a shared frozen snapshot: do NOT mutate it."""
        with self._lock:
            cached = self._hosts_cache.get(slice_id)
            if cached is not None:
                return cached
            sl = self._slices.get(slice_id)
            snap = dict(self._hosts_locked(sl)) if sl is not None else {}
            self._hosts_cache[slice_id] = snap
            return snap

    def slice_of_node(self, name: str) -> Optional[str]:
        with self._lock:
            view = self._nodes.get(name)
            if view is not None:
                return view.info.slice_id
            lazy = self._lazy_payloads.get(name)
            if lazy is not None:
                return lazy[2]
            entry = self._lazy_index.get(name)
            return entry[3] if entry is not None else None

    def node(self, name: str) -> Optional[NodeView]:
        with self._lock:
            return self._view_locked(name)

    def node_names(self) -> tuple[str, ...]:
        """Sorted node names as a SHARED frozen tuple, cached until the
        node set itself changes (per-cycle callers — the batch planner,
        /healthz, statusz — must not pay an O(fleet) sort-and-copy for
        a set that moves only when nodes register)."""
        with self._lock:
            names = self._names_cache
            if names is None:
                names = self._names_cache = tuple(sorted(
                    set(self._nodes) | set(self._lazy_index)
                    | set(self._lazy_payloads)
                ))
            return names

    def _slice_views_locked(self, slice_id: Optional[str]) -> list[NodeView]:
        """Node views of one slice — or of the WHOLE cluster only when it is
        single-slice (mixing coord sets across slices would be meaningless;
        raise instead). Callers hold ``self._lock`` (the ``_locked``
        naming is the contract tpukube-lint's shared-state pass keys on)."""
        if slice_id is None and len(self._slices) > 1:
            raise StateError(
                "coord sets are slice-local; pass slice_id on a "
                f"{len(self._slices)}-slice cluster"
            )
        # a whole-slice scan needs every view, including lazily-restored
        # ones (the background warmer usually got here first)
        self._materialize_slice_locked(slice_id)
        return [
            v for v in self._nodes.values()
            if slice_id is None or v.info.slice_id == slice_id
        ]

    def _occ_apply_locked(self, slice_id: str,
                          add: tuple = (), remove: tuple = ()) -> None:
        """Advance the slice's incremental occupied set by the same
        transition tuples the snapshot delta for this seam carries
        (callers hold ``self._lock``). Unseeded slices stay unseeded —
        the first reader pays the walk once."""
        cached = self._occ_cache.get(slice_id)
        if cached is None:
            return
        cached.difference_update(remove)
        cached.update(add)

    def _aux_apply_locked(self, slice_id: str, *,
                          unhealthy_add: tuple = (),
                          unhealthy_remove: tuple = (),
                          broken_add: tuple = (),
                          broken_remove: tuple = (),
                          used_delta: int = 0,
                          total_delta: int = 0) -> None:
        """Advance the slice's incremental unhealthy/broken/share-count
        caches by one seam's transitions (callers hold ``self._lock``;
        same contract as ``_occ_apply_locked`` — unseeded slices stay
        unseeded, the first reader pays the walk once). ``broken_*``
        are per-VIEW link report transitions: the count map tracks how
        many node views currently report each canonical link."""
        unhealthy = self._unhealthy_cache.get(slice_id)
        if unhealthy is not None:
            unhealthy.difference_update(unhealthy_remove)
            unhealthy.update(unhealthy_add)
        counts = self._broken_cache.get(slice_id)
        if counts is not None:
            for link in broken_remove:
                n = counts.get(link, 0) - 1
                if n <= 0:
                    counts.pop(link, None)
                else:
                    counts[link] = n
            for link in broken_add:
                counts[link] = counts.get(link, 0) + 1
        shares = self._share_cache.get(slice_id)
        if shares is not None:
            shares[0] += used_delta
            shares[1] += total_delta

    @staticmethod
    def _view_share_counts(view: NodeView) -> tuple[int, int]:
        """One view's (used, total) share contribution over its healthy
        chips — the per-node term both the walk and the structural-
        upsert transition math use."""
        n = view.shares_per_chip
        used = total = 0
        for chip in view.info.chips:
            if chip.health is Health.HEALTHY:
                total += n
                used += min(n, view.used_share_count(chip.index))
        return used, total

    def _walk_occupied_locked(
        self, slice_id: Optional[str]
    ) -> set[TopologyCoord]:
        """Derive a slice's occupied set the original way: walk every
        view (callers hold ``self._lock``)."""
        out: set[TopologyCoord] = set()
        for view in self._slice_views_locked(slice_id):
            for chip in view.info.chips:
                if (
                    chip.health is not Health.HEALTHY
                    or view.used_share_count(chip.index) > 0
                ):
                    out.add(chip.coord)
        return out

    def walk_occupied_coords(
        self, slice_id: Optional[str] = None
    ) -> set[TopologyCoord]:
        """``occupied_coords`` WITHOUT the incremental cache — the
        audit sentinel's independent derivation (sched/snapshot.py
        audit builds): a seam that forgot both its snapshot delta and
        its incremental occupied update must still diverge loudly
        against a ground-truth walk, so the audit never reads the very
        cache it is meant to check."""
        with self._lock:
            return self._walk_occupied_locked(slice_id)

    def occupied_coords(self, slice_id: Optional[str] = None) -> set[TopologyCoord]:
        """Coords unusable for a whole-chip/gang placement: any chip with
        used shares, plus unhealthy chips. Served from the per-slice
        incremental set (seeded by one walk, then advanced at every
        mutation seam) — the returned set is the caller's copy."""
        with self._lock:
            sid = slice_id
            if sid is None:
                # the no-argument form serves single-slice clusters and
                # raises on ambiguity (matching _slice_views_locked)
                if len(self._slices) > 1:
                    raise StateError(
                        "coord sets are slice-local; pass slice_id on "
                        f"a {len(self._slices)}-slice cluster"
                    )
                if not self._slices:
                    return set()
                sid = next(iter(self._slices))
            cached = self._occ_cache.get(sid)
            if cached is None:
                cached = self._walk_occupied_locked(sid)
                self._occ_cache[sid] = cached
            return set(cached)

    def _resolve_sid_locked(self, slice_id: Optional[str]) -> Optional[str]:
        """The no-argument form of the per-slice coord accessors serves
        single-slice clusters and raises on ambiguity (matching
        ``_slice_views_locked``); None = no slices registered yet."""
        if slice_id is not None:
            return slice_id
        if len(self._slices) > 1:
            raise StateError(
                "coord sets are slice-local; pass slice_id on a "
                f"{len(self._slices)}-slice cluster"
            )
        if not self._slices:
            return None
        return next(iter(self._slices))

    def _walk_unhealthy_locked(
        self, slice_id: Optional[str]
    ) -> set[TopologyCoord]:
        return {
            chip.coord
            for view in self._slice_views_locked(slice_id)
            for chip in view.info.chips
            if chip.health is not Health.HEALTHY
        }

    def walk_unhealthy_coords(
        self, slice_id: Optional[str] = None
    ) -> set[TopologyCoord]:
        """``unhealthy_coords`` WITHOUT the incremental cache — the
        audit sentinel's independent derivation (see
        ``walk_occupied_coords``)."""
        with self._lock:
            return self._walk_unhealthy_locked(slice_id)

    def unhealthy_coords(self, slice_id: Optional[str] = None) -> set[TopologyCoord]:
        """Coords of unhealthy chips, served from the per-slice
        incremental set (seeded by one walk, advanced at the health
        and structural seams) — the returned set is the caller's copy."""
        with self._lock:
            sid = self._resolve_sid_locked(slice_id)
            if sid is None:
                return set()
            cached = self._unhealthy_cache.get(sid)
            if cached is None:
                cached = self._walk_unhealthy_locked(sid)
                self._unhealthy_cache[sid] = cached
            return set(cached)

    def _walk_broken_locked(
        self, slice_id: Optional[str]
    ) -> dict[Link, int]:
        counts: dict[Link, int] = {}
        for view in self._slice_views_locked(slice_id):
            # distinct links per view: the count is "how many views
            # report this link", the unit the upsert transitions move
            for link in set(view.info.bad_links):
                counts[link] = counts.get(link, 0) + 1
        return counts

    def walk_broken_links(
        self, slice_id: Optional[str] = None
    ) -> set[Link]:
        """``broken_links`` WITHOUT the incremental cache (the audit
        sentinel's derivation)."""
        with self._lock:
            return set(self._walk_broken_locked(slice_id))

    def broken_links(self, slice_id: Optional[str] = None) -> set[Link]:
        """Downed ICI links, unioned over node reports. Both endpoint
        hosts may report the same link; the incremental cache counts
        reporting views per canonical link (a link leaves the set only
        when its LAST reporter withdraws it)."""
        with self._lock:
            sid = self._resolve_sid_locked(slice_id)
            if sid is None:
                return set()
            counts = self._broken_cache.get(sid)
            if counts is None:
                counts = self._walk_broken_locked(sid)
                self._broken_cache[sid] = counts
            return set(counts)

    def _walk_share_counts_locked(self, slice_id: str) -> list[int]:
        total = used = 0
        for view in self._slice_views_locked(slice_id):
            u, t = self._view_share_counts(view)
            used += u
            total += t
        return [used, total]

    def walk_slice_share_counts(self, slice_id: str) -> tuple[int, int]:
        """``slice_share_counts`` WITHOUT the incremental cache (the
        audit sentinel's derivation)."""
        with self._lock:
            used, total = self._walk_share_counts_locked(slice_id)
            return used, total

    def slice_share_counts(self, slice_id: str) -> tuple[int, int]:
        """(used, total) shares over healthy capacity of ONE slice —
        the integer pair the snapshot carries so ledger deltas can
        advance utilization in O(1) (total only moves on health or
        topology changes). Served from the per-slice incremental pair,
        seeded by one walk and advanced at the commit/release/health/
        structural seams — structural rebuilds stop walking every view
        (ROADMAP O(fleet) item)."""
        with self._lock:
            shares = self._share_cache.get(slice_id)
            if shares is None:
                shares = self._walk_share_counts_locked(slice_id)
                self._share_cache[slice_id] = shares
            return shares[0], shares[1]

    def slice_utilization(self, slice_id: str) -> float:
        """Allocated share fraction over healthy capacity of ONE slice —
        the gang layer's bin-pack signal for slice choice."""
        used, total = self.slice_share_counts(slice_id)
        return used / total if total else 0.0

    def allocation(self, pod_key: str) -> Optional[AllocResult]:
        with self._lock:
            return self._allocs.get(pod_key)

    def allocations(self) -> list[AllocResult]:
        with self._lock:
            return list(self._allocs.values())

    # -- utilization (north-star metric feed) ------------------------------
    def utilization(self) -> float:
        """Allocated share fraction over healthy capacity, 0..1 —
        summed from the per-slice incremental share counts (seeded by
        one walk per slice, advanced at every seam), so a metrics
        scrape stops walking every chip of the fleet per pull and a
        lazily-ingested fleet is counted without materializing it."""
        with self._lock:
            used = total = 0
            for sid in self._slices:
                shares = self._share_cache.get(sid)
                if shares is None:
                    shares = self._walk_share_counts_locked(sid)
                    self._share_cache[sid] = shares
                used += shares[0]
                total += shares[1]
            return used / total if total else 0.0

    def priority_of(self, pod_key: str) -> int:
        """Pod priority as committed (AllocResult carries it, and it is
        persisted in the alloc annotation, so preemption protection survives
        an extender restart). 0 for unknown pods."""
        with self._lock:
            alloc = self._allocs.get(pod_key)
            return alloc.priority if alloc is not None else 0

    # -- commit / release --------------------------------------------------
    def commit(self, alloc: AllocResult) -> None:
        """Record a bind: devices of one pod on one node. ``alloc.priority``
        is the single source of priority truth (no side table to diverge)."""
        with self._lock:
            if alloc.pod_key in self._allocs:
                raise StateError(f"{alloc.pod_key} already has an allocation")
            view = self._view_locked(alloc.node_name)
            if view is None:
                raise StateError(f"bind to unknown node {alloc.node_name}")
            n = view.shares_per_chip
            # validate first, then apply (no partial commit)
            adding: set[str] = set()
            pending_shares: dict[int, int] = {}
            for did in alloc.device_ids:
                index, frac = parse_device_id(did)
                chip = view.chip(index)
                if chip.health is not Health.HEALTHY:
                    raise StateError(f"{did}: chip unhealthy")
                if did in view.used_ids or did in adding:
                    raise StateError(f"{did}: device id already allocated")
                if frac is not None and not 0 <= frac[0] < n:
                    raise StateError(f"{did}: share index out of range")
                want = n if frac is None else 1
                have = view.free_shares(chip) - pending_shares.get(index, 0)
                if have < want:
                    raise StateError(f"{did}: insufficient free shares")
                adding.add(did)
                pending_shares[index] = pending_shares.get(index, 0) + want
            # occupied-set transitions for the snapshot delta: a chip
            # enters `occupied` when its used shares leave zero (all
            # committed chips are healthy — validated above — so the
            # used-share change equals the full added weight)
            newly_occupied = tuple(
                view.chip(index).coord
                for index in pending_shares
                if view.used_share_count(index) == 0
            )
            view.add_ids(adding)
            self._allocs[alloc.pod_key] = alloc
            self._note_gen_locked("add", alloc=alloc)
            self._occ_apply_locked(view.info.slice_id, add=newly_occupied)
            # all committed chips are healthy (validated above), so the
            # counted share delta is exactly the added weight
            self._aux_apply_locked(
                view.info.slice_id,
                used_delta=sum(pending_shares.values()),
            )
            self._epoch += 1
            self._note_delta_locked(
                slice_id=view.info.slice_id,
                occupied_add=newly_occupied,
                used_shares_delta=sum(pending_shares.values()),
                why=f"commit {alloc.pod_key}",
            )
            self._note_journal_locked(
                "commit", {"a": codec.encode_alloc(alloc)})

    def release(self, pod_key: str) -> Optional[AllocResult]:
        """Pod gone (deleted/preempted): free its shares."""
        with self._lock:
            # look up before popping: the unknown-pod path mutates
            # nothing, so it owes no epoch bump (tpukube-lint
            # epoch-discipline checks every path after a seam write)
            alloc = self._allocs.get(pod_key)
            if alloc is None:
                return None
            self._allocs.pop(pod_key, None)
            self._note_gen_locked("remove", pod_key=pod_key)
            view = self._view_locked(alloc.node_name)
            if view is None:
                # node view gone: its chips are in no slice's occupied
                # set either — an empty delta keeps the chain whole
                self._epoch += 1
                self._note_delta_locked(why=f"release {pod_key} (node gone)")
                self._note_journal_locked("release", {"p": pod_key})
                return alloc
            # snapshot delta: shares removed from HEALTHY chips reduce
            # the slice's used count (unhealthy chips were never counted
            # — nor do they leave `occupied`, which health holds)
            used_delta = 0
            indices: set[int] = set()
            for did in alloc.device_ids:
                if did not in view.used_ids:
                    continue
                index, _ = parse_device_id(did)
                indices.add(index)
                if view.chip(index).health is Health.HEALTHY:
                    used_delta -= view.id_weights.get(did, 0)
            view.remove_ids(alloc.device_ids)
            freed = tuple(
                view.chip(index).coord
                for index in sorted(indices)
                if view.used_share_count(index) == 0
                and view.chip(index).health is Health.HEALTHY
            )
            self._occ_apply_locked(view.info.slice_id, remove=freed)
            self._aux_apply_locked(view.info.slice_id,
                                   used_delta=used_delta)
            self._epoch += 1
            self._note_delta_locked(
                slice_id=view.info.slice_id,
                occupied_remove=freed,
                used_shares_delta=used_delta,
                why=f"release {pod_key}",
            )
            self._note_journal_locked("release", {"p": pod_key})
            return alloc

    # -- restart story -----------------------------------------------------
    def rebuild_from_pods(
        self, pods: list[dict[str, str]]
    ) -> list[tuple[dict[str, str], AllocResult]]:
        """Reconstruct the ledger from pod alloc annotations (each item is
        one pod's annotation dict). Returns (annotations, alloc) pairs so
        callers building further state (gang restore) keep the association
        structurally — positional re-pairing against the input would break
        silently the day this method skips one more pod."""
        restored: list[tuple[dict[str, str], AllocResult]] = []
        for annotations in pods:
            payload = annotations.get(codec.ANNO_ALLOC)
            if not payload:
                continue
            # a real cluster can hold annotations we did not write
            # (malformed edits, pods bound to vanished nodes): one bad
            # pod must not abort the whole rebuild. LOUD skips — until
            # reconciled the ledger under-counts the skipped pod's chips.
            try:
                alloc = codec.decode_alloc(payload)
            except codec.CodecError as e:
                # undecodable payloads carry no pod key; log a snippet so
                # the operator can find the offending annotation
                log.error("rebuild: undecodable alloc annotation (%s): "
                          "%.120s", e, payload)
                continue
            try:
                self.commit(alloc)
            except StateError as e:
                log.error("rebuild: skipping %s (%s) — the ledger "
                          "under-counts its chips until reconciled",
                          alloc.pod_key, e)
                continue
            restored.append((annotations, alloc))
        return restored

    # -- durable-state checkpoint (sched/journal.py) -------------------------
    def checkpoint_doc(self, cache: dict) -> tuple[dict, list]:
        """The ledger as a Checkpoint: a HEAD fragment (slice meshes,
        compact host blobs, alloc objects + their payload signatures)
        plus per-node LINE entries the journal writes after the head —
        so a warm restore parses the small head eagerly and each node
        line lazily on first touch (``_view_locked``).

        ``cache`` memoizes per-node serialized lines keyed on payload
        identity, so steady-state captures cost O(allocs + changed
        nodes), not O(fleet). A still-LAZY node yields a ``("ref", ...)``
        entry naming its bytes in the PREVIOUS checkpoint file — the
        journal's drain thread copies them verbatim (this capture runs
        under the decision lock and must not read disk). Runs under
        ``self._lock``; serialization of changed nodes happens here (in
        memory), disk belongs to the drain thread."""
        node_cache = cache.setdefault("nodes", {})
        alloc_cache = cache.setdefault("allocs", {})
        with self._lock:
            entries: list[tuple] = []
            for name, view in self._nodes.items():
                cached = node_cache.get(name)
                if cached is not None and cached[0] is view.raw_payload:
                    entries.append(cached[1])
                    continue
                line = json.dumps(_node_doc(view),
                                  separators=(",", ":"))
                raw_payload = view.raw_payload.encode("utf-8")
                entry = ("line", name, line,
                         zlib.crc32(line.encode("utf-8")),
                         view.info.slice_id,
                         zlib.crc32(raw_payload), len(raw_payload))
                node_cache[name] = (view.raw_payload, entry)
                entries.append(entry)
            for name, (payload, annotations, sid) in \
                    self._lazy_payloads.items():
                # a still-lazy bulk-ingest node rides as its RAW
                # annotations (this capture must not decode the fleet);
                # the loader keeps it lazy and decodes on first touch
                cached = node_cache.get(name)
                if cached is not None and cached[0] is payload:
                    entries.append(cached[1])
                    continue
                line = json.dumps(
                    {"n": name, "slice": sid, "anno": annotations},
                    separators=(",", ":"))
                raw_payload = payload.encode("utf-8")
                entry = ("line", name, line,
                         zlib.crc32(line.encode("utf-8")), sid,
                         zlib.crc32(raw_payload), len(raw_payload))
                node_cache[name] = (payload, entry)
                entries.append(entry)
            for name, le in self._lazy_index.items():
                off, length, crc, sid, pcrc, plen = le
                entries.append(("ref", name, off, length, crc, sid,
                                pcrc, plen))
            allocs = []
            alloc_index: dict[str, tuple[int, int]] = {}
            for key, alloc in self._allocs.items():
                cached = alloc_cache.get(key)
                if cached is None or cached[0] is not alloc:
                    payload = codec.encode_alloc(alloc).encode("utf-8")
                    cached = alloc_cache[key] = (
                        alloc, codec.alloc_obj(alloc),
                        (zlib.crc32(payload), len(payload)),
                    )
                allocs.append(cached[1])
                alloc_index[key] = cached[2]
            head = {
                "epoch": self._epoch,
                # the alloc generation rides the checkpoint so a warm
                # recovery RESUMES the numbering (never regresses);
                # resync cursors from the dead incarnation still full-
                # read once — the incarnation token changed
                "gen": self._generation,
                "slices": {
                    sid: [list(sl.mesh.dims), list(sl.mesh.host_block),
                          list(sl.mesh.torus)]
                    for sid, sl in self._slices.items()
                },
                "hosts": {sid: self._hosts_blob_locked(sl)
                          for sid, sl in self._slices.items()},
                "allocs": allocs,
                "alloc_index": {k: list(v)
                                for k, v in alloc_index.items()},
            }
            if self._cordoned:
                # only-when-non-empty: checkpoint bytes stay identical
                # with the drain plane off (the off-is-off golden)
                head["cordoned"] = sorted(self._cordoned)
            return head, entries

    def _hosts_blob_locked(self, sl: SliceView) -> str:
        """The slice's host map as the compact checkpoint blob, cached
        until the map mutates (a still-pending blob round-trips
        verbatim — no expansion just to re-serialize)."""
        if sl.pending_hosts is not None:
            return sl.pending_hosts
        if sl.hosts_blob is None:
            sl.hosts_blob = ";".join(
                f"{c[0]},{c[1]},{c[2]}={h}"
                for c, h in sl.host_by_coord.items()
            )
        return sl.hosts_blob

    def restore_checkpoint(self, head: dict, fd: Optional[int],
                           node_index: dict[str, list]) -> int:
        """Rebuild the ledger from a Checkpoint HEAD onto a fresh
        instance (recovery's warm path): slices and allocations
        eagerly, node views LAZILY — ``node_index`` positions each
        node's line inside the open checkpoint file ``fd`` (ownership
        transfers here; closed when the last lazy node materializes).
        Unlike ``commit``, alloc application skips health validation:
        the checkpoint recorded reality at capture time — a chip that
        sickened later must not drop a running pod from the ledger.
        Returns the allocations restored; raises StateError on a
        non-fresh ledger (recovery constructs a new extender, never
        restores over one)."""
        with self._lock:
            if (self._nodes or self._allocs or self._lazy_index
                    or self._lazy_payloads):
                raise StateError(
                    "restore_checkpoint requires a fresh ledger"
                )
            self._epoch = int(head.get("epoch", 0))
            self._generation = int(head.get("gen", 0))
            self._cordoned = set(head.get("cordoned", ()))
            for sid, (dims, block, torus) in head["slices"].items():
                self._slices[sid] = SliceView(
                    mesh=MeshSpec(
                        dims=tuple(int(d) for d in dims),
                        host_block=tuple(int(b) for b in block),
                        torus=tuple(bool(t) for t in torus),
                    ),
                    pending_hosts=head["hosts"].get(sid, ""),
                )
            self._lazy_fd = fd
            for name, entry in node_index.items():
                off, length, crc, sid, pcrc, plen = entry
                self._lazy_index[name] = (off, length, crc, sid,
                                          pcrc, plen)
            restored = 0
            for obj in head["allocs"]:
                try:
                    alloc = codec.alloc_from_obj(obj)
                except codec.CodecError as e:
                    log.error("checkpoint restore: undecodable alloc "
                              "(%s)", e)
                    continue
                if (alloc.node_name not in self._lazy_index
                        and alloc.node_name not in self._nodes):
                    log.error("checkpoint restore: %s names unknown node "
                              "%s; skipped", alloc.pod_key,
                              alloc.node_name)
                    continue
                self._allocs[alloc.pod_key] = alloc
                # occupancy re-applies at materialization (the alloc
                # list is the occupancy's single home — node lines
                # deliberately carry none, so the per-payload line
                # cache never goes stale under churn)
                self._lazy_allocs.setdefault(
                    alloc.node_name, []).append(alloc)
                restored += 1
            self._names_cache = None
            self._epoch += 1
            self._note_delta_locked(full=True, why="checkpoint restore")
            return restored

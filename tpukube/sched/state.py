"""Cluster state as the extender sees it (L5 support).

SURVEY.md §6 (checkpoint/resume): the control plane is deliberately
stateless — node truth arrives in ``node-topology`` annotations with each
webhook call, and allocations live in pod annotations. The only in-memory
structure is this ledger of commitments, and it is reconstructible from pod
annotations after an extender restart (``rebuild_from_pods``), which the
tests exercise.

Occupancy accounting is share-granular: a whole-chip node is just the
n=1 case of a vTPU node, so one ledger covers both resources.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from tpukube.core import codec
from tpukube.core.mesh import MeshSpec
from tpukube.core.types import (
    AllocResult,
    ChipInfo,
    Health,
    Link,
    NodeInfo,
    TopologyCoord,
    parse_device_id,
)


log = logging.getLogger("tpukube.state")


class StateError(RuntimeError):
    pass


@dataclass
class NodeView:
    """One node's decoded annotation + live occupancy, tracked at device-id
    granularity (a count would re-mint a released share's id while its twin
    is still live — ids are the unit of truth, counts are derived).
    ``share_counts`` is a per-chip cache of those derived counts, kept in
    lockstep by add_ids/remove_ids (used_share_count is the hottest call
    of every webhook — parsing ids per query was measurable)."""

    info: NodeInfo
    used_ids: set[str] = field(default_factory=set)
    share_counts: dict[int, int] = field(default_factory=dict)
    # weight each id contributed to share_counts AT COMMIT TIME — release
    # must subtract exactly that, not a recomputation: a node whose
    # shares_per_chip annotation changes under live allocations would
    # otherwise leak counts permanently
    id_weights: dict[str, int] = field(default_factory=dict)
    # verbatim annotation payload this view was decoded from; upsert_node
    # skips re-decoding when a webhook carries the identical string (hot:
    # every /filter and /prioritize re-sends every node's annotations)
    raw_payload: str = ""
    # decoded tpu.qiniu.com/health-summary annotation (obs telemetry),
    # None when the node agent predates it; the /statusz fleet rollup
    # prefers these counts and falls back to chip health otherwise
    health_summary: Optional[dict] = None

    # coord -> chip index, built on first use (views are re-created per
    # decoded annotation, never re-pointed at different chips); the bind
    # path queries this per planned coord — a linear chip scan there was
    # round-2 weak #2
    _coord_index: dict[TopologyCoord, int] = field(default_factory=dict)
    # occupancy version, bumped by add_ids/remove_ids: memoizes the
    # derived free-chip list and free-share total, which every webhook
    # recomputes per node (health changes arrive as NEW views via
    # upsert_node, so version-only invalidation is sound)
    _version: int = 0
    _free_cache: Optional[tuple[int, list[ChipInfo]]] = None
    _free_shares_cache: Optional[tuple[int, int]] = None

    @property
    def shares_per_chip(self) -> int:
        return max(1, self.info.shares_per_chip)

    def chip(self, index: int) -> ChipInfo:
        return self.info.chip_by_index(index)

    def index_at(self, coord: TopologyCoord) -> int:
        if not self._coord_index:
            self._coord_index = {c.coord: c.index for c in self.info.chips}
        try:
            return self._coord_index[coord]
        except KeyError:
            raise StateError(
                f"no chip at {coord} on {self.info.name}"
            ) from None

    def add_ids(self, ids) -> None:
        self._version += 1
        for did in ids:
            i, frac = parse_device_id(did)
            self.used_ids.add(did)
            weight = 1 if frac is not None else self.shares_per_chip
            self.id_weights[did] = weight
            self.share_counts[i] = self.share_counts.get(i, 0) + weight

    def remove_ids(self, ids) -> None:
        self._version += 1
        for did in ids:
            if did not in self.used_ids:
                continue
            i, _ = parse_device_id(did)
            self.used_ids.discard(did)
            weight = self.id_weights.pop(did, 0)
            left = self.share_counts.get(i, 0) - weight
            if left > 0:
                self.share_counts[i] = left
            else:
                self.share_counts.pop(i, None)

    def used_share_count(self, index: int) -> int:
        return self.share_counts.get(index, 0)

    def used_frac_ks(self, index: int) -> set[int]:
        out = set()
        for did in self.used_ids:
            i, frac = parse_device_id(did)
            if i == index and frac is not None:
                out.add(frac[0])
        return out

    def free_shares(self, chip: ChipInfo) -> int:
        if chip.health is not Health.HEALTHY:
            return 0
        return self.shares_per_chip - self.used_share_count(chip.index)

    def total_free_shares(self) -> int:
        cached = self._free_shares_cache
        if cached is not None and cached[0] == self._version:
            return cached[1]
        total = sum(self.free_shares(c) for c in self.info.chips)
        self._free_shares_cache = (self._version, total)
        return total

    def free_chips(self) -> list[ChipInfo]:
        """Chips with ALL shares free (placeable as whole units).
        Shared memoized list — callers must not mutate it."""
        cached = self._free_cache
        if cached is not None and cached[0] == self._version:
            return cached[1]
        out = [
            c
            for c in self.info.chips
            if self.free_shares(c) == self.shares_per_chip
        ]
        self._free_cache = (self._version, out)
        return out


@dataclass
class SliceView:
    """One ICI domain: its mesh geometry plus the data-driven coord->host
    map built from node annotations (host naming is a sim convention, not a
    contract — the annotation's chip coords are the truth)."""

    mesh: MeshSpec
    host_by_coord: dict[TopologyCoord, str] = field(default_factory=dict)


class ClusterState:
    """Thread-safe ledger: per-slice node views + per-chip share occupancy.

    The extender serves concurrent webhook calls; all mutation goes through
    this object's lock (SURVEY.md §9.3: reservations must be linearizable
    under concurrent filter calls — the gang layer in M7 builds on this).

    A cluster holds one or more ICI slices (SURVEY.md §3 "distributed
    communication backend": ICI intra-slice, DCN inter-slice). Chip coords
    are slice-local, so every coord-set accessor takes a slice id; the
    no-argument forms serve the common single-slice cluster and raise on
    ambiguity rather than silently mixing coordinate spaces.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._nodes: dict[str, NodeView] = {}
        self._slices: dict[str, SliceView] = {}
        self._allocs: dict[str, AllocResult] = {}  # pod key -> commitment
        # frozen coord->host snapshots handed to hot-path callers; rebuilt
        # lazily after any host-map mutation (annotations rarely change)
        self._hosts_cache: dict[str, dict[TopologyCoord, str]] = {}
        # ledger epoch: bumped by EVERY mutation (node upsert, commit,
        # release — rebuild_from_pods goes through commit). The epoch-
        # cached scheduling snapshot (sched/snapshot.py) keys its
        # validity on this, so a missed bump here would serve stale
        # placements — treat any new mutation path as epoch-bumping.
        self._epoch = 0
        # snapshot delta sink (sched/snapshot.py SnapshotCache, wired
        # by the owning GangManager): every epoch bump pairs with a
        # _note_delta so the cache can advance O(Δ) instead of
        # rebuilding. A bump without a note degrades to a full rebuild
        # (log gap), never to a stale cache.
        self._delta_sink = None

    def set_delta_sink(self, sink) -> None:
        """Attach the snapshot cache's delta log (None detaches)."""
        with self._lock:
            self._delta_sink = sink

    def _note_delta_locked(self, full: bool = False,
                    slice_id: Optional[str] = None,
                    occupied_add: tuple = (), occupied_remove: tuple = (),
                    used_shares_delta: int = 0, why: str = "") -> None:
        """Record the bump just taken (callers hold ``self._lock`` and
        call this right after ``self._epoch += 1``). Import is lazy and
        one-directional: snapshot.py never imports state."""
        sink = self._delta_sink
        if sink is None:
            return
        from tpukube.sched.snapshot import SnapshotDelta

        sink.note(SnapshotDelta(
            kind="ledger", epoch=self._epoch, full=full,
            slice_id=slice_id, occupied_add=occupied_add,
            occupied_remove=occupied_remove,
            used_shares_delta=used_shares_delta, why=why,
        ))

    def epoch(self) -> int:
        """Monotonic mutation counter (the snapshot cache's key half)."""
        with self._lock:
            return self._epoch

    # -- node ingestion ----------------------------------------------------
    def upsert_node(self, name: str, annotations: dict[str, str]) -> bool:
        """Decode and store a node's topology annotation. Returns False when
        the node carries no tpukube annotation (not ours to manage)."""
        payload = annotations.get(codec.ANNO_NODE_TOPOLOGY)
        if payload is None:
            return False
        with self._lock:
            prev = self._nodes.get(name)
            if prev is not None and prev.raw_payload == payload:
                return True  # unchanged annotation: keep the decoded view
        decoded = codec.node_from_annotations(name, annotations)
        if decoded is None:
            return False
        info, mesh = decoded
        with self._lock:
            sl = self._slices.get(info.slice_id)
            if sl is None:
                sl = self._slices[info.slice_id] = SliceView(mesh=mesh)
                # the slice set feeds snapshot.slice_ids(): bump at the
                # seam itself, not only at the end of the upsert — the
                # validation raises below must not leave a registered
                # slice invisible to the epoch cache (found by
                # tpukube-lint's epoch-discipline pass)
                self._epoch += 1
                # a new slice is structural: the delta path cannot
                # patch a slice the base snapshot never held
                self._note_delta_locked(full=True,
                                 why=f"slice {info.slice_id} registered")
            elif sl.mesh != mesh:
                raise StateError(
                    f"node {name} reports mesh {mesh.dims} for slice "
                    f"{info.slice_id}, which has {sl.mesh.dims} — nodes of "
                    f"one slice must agree on its geometry"
                )
            prev = self._nodes.get(name)
            if prev is not None and prev.info.slice_id != info.slice_id:
                raise StateError(
                    f"node {name} moved from slice {prev.info.slice_id} "
                    f"to {info.slice_id} — drop and re-add the node"
                )
            if (
                prev is not None
                and prev.used_ids
                and prev.info.shares_per_chip != info.shares_per_chip
            ):
                # a sharing-mode switch under live allocations cannot be
                # accounted (committed ids carry the OLD mode's weights;
                # mixing modes double-books chips) — drain the node first
                raise StateError(
                    f"node {name} changed shares_per_chip "
                    f"{prev.info.shares_per_chip} -> {info.shares_per_chip} "
                    f"with {len(prev.used_ids)} live allocations — drain "
                    f"the node before switching sharing mode"
                )
            # validate EVERY claim before mutating anything: a partial
            # apply would leave phantom claims with no owner on error
            for chip in info.chips:
                claimed = sl.host_by_coord.get(chip.coord)
                if claimed is not None and claimed != name:
                    raise StateError(
                        f"nodes {claimed} and {name} both claim chip "
                        f"{tuple(chip.coord)} in slice {info.slice_id}"
                    )
            if prev is not None:
                for chip in prev.info.chips:
                    if sl.host_by_coord.get(chip.coord) == name:
                        del sl.host_by_coord[chip.coord]
            for chip in info.chips:
                sl.host_by_coord[chip.coord] = name
            self._hosts_cache.pop(info.slice_id, None)
            summary = None
            raw_summary = annotations.get(codec.ANNO_HEALTH_SUMMARY)
            if raw_summary:
                try:
                    summary = codec.decode_health_summary(raw_summary)
                except codec.CodecError as e:
                    # a malformed summary must not reject the topology —
                    # the rollup simply falls back to chip health
                    log.warning("node %s: undecodable health summary: %s",
                                name, e)
            view = NodeView(info=info, raw_payload=payload,
                            health_summary=summary)
            if prev is not None:
                view.used_ids = prev.used_ids
                view.share_counts = prev.share_counts
                view.id_weights = prev.id_weights
            self._nodes[name] = view
            self._epoch += 1
            # a CHANGED node payload may move health, links, topology,
            # or sharing mode — all structural for the snapshot (they
            # shift unhealthy/broken sets and the healthy-share totals
            # the delta math assumes constant): full-rebuild marker.
            # The unchanged-payload early return above keeps the hot
            # webhook resend path bump- and delta-free.
            self._note_delta_locked(full=True, why=f"node {name} re-annotated")
        return True

    # -- views -------------------------------------------------------------
    @property
    def mesh(self) -> Optional[MeshSpec]:
        """The sole slice's mesh (single-slice clusters). None before any
        node is known; StateError when several slices exist — callers on a
        multi-slice cluster must name the slice (slice_mesh)."""
        with self._lock:
            if not self._slices:
                return None
            if len(self._slices) > 1:
                raise StateError(
                    f"cluster has {len(self._slices)} slices; use "
                    f"slice_mesh(slice_id)"
                )
            return next(iter(self._slices.values())).mesh

    def slice_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._slices)

    def slice_mesh(self, slice_id: str) -> MeshSpec:
        with self._lock:
            sl = self._slices.get(slice_id)
            if sl is None:
                raise StateError(f"unknown slice {slice_id!r}")
            return sl.mesh

    def host_at(self, slice_id: str, coord: TopologyCoord) -> Optional[str]:
        """Node owning a chip coord within a slice (annotation-derived)."""
        with self._lock:
            sl = self._slices.get(slice_id)
            return sl.host_by_coord.get(coord) if sl is not None else None

    def hosts_by_coord(self, slice_id: str) -> dict[TopologyCoord, str]:
        """Snapshot of a slice's coord->node map — one lock round-trip for
        callers that look up many coords (the per-node gang hot path).
        The returned dict is a shared frozen snapshot: do NOT mutate it."""
        with self._lock:
            cached = self._hosts_cache.get(slice_id)
            if cached is not None:
                return cached
            sl = self._slices.get(slice_id)
            snap = dict(sl.host_by_coord) if sl is not None else {}
            self._hosts_cache[slice_id] = snap
            return snap

    def slice_of_node(self, name: str) -> Optional[str]:
        with self._lock:
            view = self._nodes.get(name)
            return view.info.slice_id if view is not None else None

    def node(self, name: str) -> Optional[NodeView]:
        with self._lock:
            return self._nodes.get(name)

    def node_names(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    def _slice_views_locked(self, slice_id: Optional[str]) -> list[NodeView]:
        """Node views of one slice — or of the WHOLE cluster only when it is
        single-slice (mixing coord sets across slices would be meaningless;
        raise instead). Callers hold ``self._lock`` (the ``_locked``
        naming is the contract tpukube-lint's shared-state pass keys on)."""
        if slice_id is None and len(self._slices) > 1:
            raise StateError(
                "coord sets are slice-local; pass slice_id on a "
                f"{len(self._slices)}-slice cluster"
            )
        return [
            v for v in self._nodes.values()
            if slice_id is None or v.info.slice_id == slice_id
        ]

    def occupied_coords(self, slice_id: Optional[str] = None) -> set[TopologyCoord]:
        """Coords unusable for a whole-chip/gang placement: any chip with
        used shares, plus unhealthy chips."""
        with self._lock:
            out: set[TopologyCoord] = set()
            for view in self._slice_views_locked(slice_id):
                for chip in view.info.chips:
                    if (
                        chip.health is not Health.HEALTHY
                        or view.used_share_count(chip.index) > 0
                    ):
                        out.add(chip.coord)
            return out

    def unhealthy_coords(self, slice_id: Optional[str] = None) -> set[TopologyCoord]:
        with self._lock:
            return {
                chip.coord
                for view in self._slice_views_locked(slice_id)
                for chip in view.info.chips
                if chip.health is not Health.HEALTHY
            }

    def broken_links(self, slice_id: Optional[str] = None) -> set[Link]:
        """Downed ICI links, unioned over node reports. Both endpoint hosts
        may report the same link; canonical pairs dedupe them."""
        with self._lock:
            return {
                link
                for view in self._slice_views_locked(slice_id)
                for link in view.info.bad_links
            }

    def slice_share_counts(self, slice_id: str) -> tuple[int, int]:
        """(used, total) shares over healthy capacity of ONE slice —
        the integer pair the snapshot carries so ledger deltas can
        advance utilization in O(1) (total only moves on health or
        topology changes, which are full-rebuild markers)."""
        with self._lock:
            total = used = 0
            for view in self._slice_views_locked(slice_id):
                n = view.shares_per_chip
                for chip in view.info.chips:
                    if chip.health is Health.HEALTHY:
                        total += n
                        used += min(n, view.used_share_count(chip.index))
            return used, total

    def slice_utilization(self, slice_id: str) -> float:
        """Allocated share fraction over healthy capacity of ONE slice —
        the gang layer's bin-pack signal for slice choice."""
        used, total = self.slice_share_counts(slice_id)
        return used / total if total else 0.0

    def allocation(self, pod_key: str) -> Optional[AllocResult]:
        with self._lock:
            return self._allocs.get(pod_key)

    def allocations(self) -> list[AllocResult]:
        with self._lock:
            return list(self._allocs.values())

    # -- utilization (north-star metric feed) ------------------------------
    def utilization(self) -> float:
        """Allocated share fraction over healthy capacity, 0..1."""
        with self._lock:
            total = 0
            used = 0
            for view in self._nodes.values():
                n = view.shares_per_chip
                for chip in view.info.chips:
                    if chip.health is Health.HEALTHY:
                        total += n
                        used += min(n, view.used_share_count(chip.index))
            return used / total if total else 0.0

    def priority_of(self, pod_key: str) -> int:
        """Pod priority as committed (AllocResult carries it, and it is
        persisted in the alloc annotation, so preemption protection survives
        an extender restart). 0 for unknown pods."""
        with self._lock:
            alloc = self._allocs.get(pod_key)
            return alloc.priority if alloc is not None else 0

    # -- commit / release --------------------------------------------------
    def commit(self, alloc: AllocResult) -> None:
        """Record a bind: devices of one pod on one node. ``alloc.priority``
        is the single source of priority truth (no side table to diverge)."""
        with self._lock:
            if alloc.pod_key in self._allocs:
                raise StateError(f"{alloc.pod_key} already has an allocation")
            view = self._nodes.get(alloc.node_name)
            if view is None:
                raise StateError(f"bind to unknown node {alloc.node_name}")
            n = view.shares_per_chip
            # validate first, then apply (no partial commit)
            adding: set[str] = set()
            pending_shares: dict[int, int] = {}
            for did in alloc.device_ids:
                index, frac = parse_device_id(did)
                chip = view.chip(index)
                if chip.health is not Health.HEALTHY:
                    raise StateError(f"{did}: chip unhealthy")
                if did in view.used_ids or did in adding:
                    raise StateError(f"{did}: device id already allocated")
                if frac is not None and not 0 <= frac[0] < n:
                    raise StateError(f"{did}: share index out of range")
                want = n if frac is None else 1
                have = view.free_shares(chip) - pending_shares.get(index, 0)
                if have < want:
                    raise StateError(f"{did}: insufficient free shares")
                adding.add(did)
                pending_shares[index] = pending_shares.get(index, 0) + want
            # occupied-set transitions for the snapshot delta: a chip
            # enters `occupied` when its used shares leave zero (all
            # committed chips are healthy — validated above — so the
            # used-share change equals the full added weight)
            newly_occupied = tuple(
                view.chip(index).coord
                for index in pending_shares
                if view.used_share_count(index) == 0
            )
            view.add_ids(adding)
            self._allocs[alloc.pod_key] = alloc
            self._epoch += 1
            self._note_delta_locked(
                slice_id=view.info.slice_id,
                occupied_add=newly_occupied,
                used_shares_delta=sum(pending_shares.values()),
                why=f"commit {alloc.pod_key}",
            )

    def release(self, pod_key: str) -> Optional[AllocResult]:
        """Pod gone (deleted/preempted): free its shares."""
        with self._lock:
            # look up before popping: the unknown-pod path mutates
            # nothing, so it owes no epoch bump (tpukube-lint
            # epoch-discipline checks every path after a seam write)
            alloc = self._allocs.get(pod_key)
            if alloc is None:
                return None
            self._allocs.pop(pod_key, None)
            view = self._nodes.get(alloc.node_name)
            if view is None:
                # node view gone: its chips are in no slice's occupied
                # set either — an empty delta keeps the chain whole
                self._epoch += 1
                self._note_delta_locked(why=f"release {pod_key} (node gone)")
                return alloc
            # snapshot delta: shares removed from HEALTHY chips reduce
            # the slice's used count (unhealthy chips were never counted
            # — nor do they leave `occupied`, which health holds)
            used_delta = 0
            indices: set[int] = set()
            for did in alloc.device_ids:
                if did not in view.used_ids:
                    continue
                index, _ = parse_device_id(did)
                indices.add(index)
                if view.chip(index).health is Health.HEALTHY:
                    used_delta -= view.id_weights.get(did, 0)
            view.remove_ids(alloc.device_ids)
            freed = tuple(
                view.chip(index).coord
                for index in sorted(indices)
                if view.used_share_count(index) == 0
                and view.chip(index).health is Health.HEALTHY
            )
            self._epoch += 1
            self._note_delta_locked(
                slice_id=view.info.slice_id,
                occupied_remove=freed,
                used_shares_delta=used_delta,
                why=f"release {pod_key}",
            )
            return alloc

    # -- restart story -----------------------------------------------------
    def rebuild_from_pods(
        self, pods: list[dict[str, str]]
    ) -> list[tuple[dict[str, str], AllocResult]]:
        """Reconstruct the ledger from pod alloc annotations (each item is
        one pod's annotation dict). Returns (annotations, alloc) pairs so
        callers building further state (gang restore) keep the association
        structurally — positional re-pairing against the input would break
        silently the day this method skips one more pod."""
        restored: list[tuple[dict[str, str], AllocResult]] = []
        for annotations in pods:
            payload = annotations.get(codec.ANNO_ALLOC)
            if not payload:
                continue
            # a real cluster can hold annotations we did not write
            # (malformed edits, pods bound to vanished nodes): one bad
            # pod must not abort the whole rebuild. LOUD skips — until
            # reconciled the ledger under-counts the skipped pod's chips.
            try:
                alloc = codec.decode_alloc(payload)
            except codec.CodecError as e:
                # undecodable payloads carry no pod key; log a snippet so
                # the operator can find the offending annotation
                log.error("rebuild: undecodable alloc annotation (%s): "
                          "%.120s", e, payload)
                continue
            try:
                self.commit(alloc)
            except StateError as e:
                log.error("rebuild: skipping %s (%s) — the ledger "
                          "under-counts its chips until reconciled",
                          alloc.pod_key, e)
                continue
            restored.append((annotations, alloc))
        return restored

"""Kubernetes API JSON <-> core types.

The extender webhook bodies are fixed by kube-scheduler (SURVEY.md §2 L5:
"the scheduler extender JSON schema ExtenderArgs/ExtenderFilterResult/
HostPriorityList — fixed by Kubernetes"). This module converts between
those wire dicts and the framework's PodInfo/NodeInfo, so the extender
logic never touches raw JSON.

Field names follow the upstream scheduler-extender v1 API (capitalized:
"Pod", "Nodes", "FailedNodes", "Host", "Score"); pod/node objects follow
core v1 (lowercase metadata/spec).
"""

from __future__ import annotations

from typing import Any, Optional

from tpukube.core import codec
from tpukube.core.types import ContainerInfo, PodInfo, ResourceList


class KubeSchemaError(ValueError):
    pass


def pod_from_k8s(obj: dict[str, Any]) -> PodInfo:
    """v1.Pod dict -> PodInfo (only the fields this framework reasons on)."""
    if not isinstance(obj, dict):
        raise KubeSchemaError("Pod must be a JSON object")
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    name = meta.get("name")
    if not name:
        raise KubeSchemaError("Pod.metadata.name missing")
    containers = []
    for c in spec.get("containers") or []:
        requests_raw = ((c.get("resources") or {}).get("requests")) or {}
        requests = ResourceList()
        for k, v in requests_raw.items():
            try:
                requests[k] = int(v)
            except (TypeError, ValueError):
                # non-integer quantities (cpu "500m", memory "1Gi") are not
                # device resources; this framework only meters whole devices
                continue
        containers.append(ContainerInfo(name=c.get("name", ""), requests=requests))
    pod = PodInfo(
        name=name,
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        containers=containers,
        priority=int(spec.get("priority") or 0),
        annotations=dict(meta.get("annotations") or {}),
        labels=dict(meta.get("labels") or {}),
        node_name=spec.get("nodeName", ""),
    )
    codec.attach_group(pod)
    return pod


def pod_to_k8s(pod: PodInfo) -> dict[str, Any]:
    """PodInfo -> v1.Pod dict, the inverse of :func:`pod_from_k8s` for
    the fields this framework reasons on. The sharded router's
    subprocess transport ships driver-admitted pods to worker daemons
    with this; round-tripping through ``pod_from_k8s`` on the worker
    reconstructs an equivalent PodInfo (the gang group rides its
    annotations, re-attached by ``codec.attach_group``)."""
    annotations = dict(pod.annotations)
    if pod.group is not None:
        annotations.update(codec.pod_group_annotations(pod.group))
    spec: dict[str, Any] = {
        "priority": pod.priority,
        "containers": [
            {
                "name": c.name,
                "resources": {
                    "requests": {k: str(v)
                                 for k, v in c.requests.items()}
                },
            }
            for c in pod.containers
        ],
    }
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    return {
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "annotations": annotations,
            "labels": dict(pod.labels),
        },
        "spec": spec,
    }


def node_name_and_annotations(obj: dict[str, Any]) -> tuple[str, dict[str, str]]:
    if not isinstance(obj, dict):
        raise KubeSchemaError("Node must be a JSON object")
    meta = obj.get("metadata") or {}
    name = meta.get("name")
    if not name:
        raise KubeSchemaError("Node.metadata.name missing")
    return name, dict(meta.get("annotations") or {})


def parse_extender_args(
    body: dict[str, Any],
) -> tuple[PodInfo, Optional[list[dict[str, Any]]], Optional[list[str]]]:
    """ExtenderArgs -> (pod, raw node objects | None, node names | None).

    At most one of the last two is set. ``NodeNames`` is the
    nodeCacheCapable mode of the upstream extender protocol: the
    scheduler sends only names and the extender answers from its own node
    cache (here: ClusterState, fed by the annotation syncer) — the big
    per-webhook node payload disappears from the hot path.

    ``NodesCached: true`` (a sim-harness extension, ISSUE 14) takes
    nodeCacheCapable to its conclusion: the candidate set is "every
    node the extender already knows" and the body names NONE of them —
    both returns are None and the handler expands from its own cache.
    Re-listing 10k unchanged names per sampled webhook was a measured
    O(nodes) term of the kilonode drives; placements are parity-tested
    against the protocol-faithful body."""
    if not isinstance(body, dict):
        raise KubeSchemaError("ExtenderArgs must be a JSON object")
    pod_obj = body.get("Pod")
    if pod_obj is None:
        raise KubeSchemaError("ExtenderArgs.Pod missing")
    pod = pod_from_k8s(pod_obj)
    nodes = (body.get("Nodes") or {}).get("Items")
    if nodes is not None:
        return pod, list(nodes), None
    if body.get("NodesCached") is True:
        return pod, None, None
    names = body.get("NodeNames")
    if names is None:
        raise KubeSchemaError(
            "ExtenderArgs carries neither Nodes.Items, NodeNames, "
            "nor NodesCached"
        )
    if not isinstance(names, list) or not all(
        isinstance(n, str) for n in names
    ):
        raise KubeSchemaError(
            "ExtenderArgs.NodeNames must be a list of strings"
        )
    return pod, None, list(names)


def filter_result(
    feasible: list[dict[str, Any]],
    failed: dict[str, str],
    error: str = "",
) -> dict[str, Any]:
    return {
        "Nodes": {"Items": feasible},
        "NodeNames": [
            (n.get("metadata") or {}).get("name") for n in feasible
        ],
        "FailedNodes": failed,
        "Error": error,
    }


def filter_result_names(
    feasible_names: list[str],
    failed: dict[str, str],
    error: str = "",
) -> dict[str, Any]:
    """ExtenderFilterResult in nodeCacheCapable mode: names only."""
    return {
        "NodeNames": list(feasible_names),
        "FailedNodes": failed,
        "Error": error,
    }


def host_priority_list(scores: dict[str, int]) -> list[dict[str, Any]]:
    return [{"Host": h, "Score": s} for h, s in sorted(scores.items())]


def parse_binding_args(body: dict[str, Any]) -> tuple[str, str, str, str]:
    """ExtenderBindingArgs -> (name, namespace, uid, node)."""
    if not isinstance(body, dict):
        raise KubeSchemaError("ExtenderBindingArgs must be a JSON object")
    try:
        return (
            body["PodName"],
            body.get("PodNamespace", "default"),
            body.get("PodUID", ""),
            body["Node"],
        )
    except KeyError as e:
        raise KubeSchemaError(f"ExtenderBindingArgs missing {e}") from e


def binding_result(error: Optional[str] = None) -> dict[str, Any]:
    return {"Error": error or ""}

"""Graceful drain / decommission choreography (ISSUE 19 tentpole).

PR 15 made capacity *arrival* O(Δ); this module owns the other
direction. A drain takes a node set (usually a whole ICI slice) out of
service in three phases, none of which is a full rebuild:

  1. **Cordon** — ``ClusterState.set_cordon`` marks the nodes; their
     chips leave every placement sweep (``SliceSnapshot.blocked_sweep``
     masks them like occupancy) while live allocations keep serving.
     One epoch/delta/journal seam per batch, so the cordon rides the
     WAL and checkpoints like any other ledger mutation.
  2. **Migrate-or-preempt** — residents are evicted through the SAME
     victim machinery gang preemption uses (``Extender._apply_victims``:
     gangs dissolve wholesale, plain pods release + queue on the
     eviction bus), under a bounded disruption budget: at most
     ``drain_max_concurrent_moves`` workloads per tick, cheapest
     priority first, at most ``drain_tenant_budget`` pods per tenant
     per tick (0 = uncapped). Each evicted pod's provenance chain gains
     a ``drain_evict`` stage naming the drain — "where did my chips
     go" answers "maintenance", not silence.
  3. **Un-ingest** — once no resident remains, ``remove_nodes`` (the
     inverse of ``ingest_nodes``) drops views/lazy payloads, retires
     the per-slice incremental caches, deletes empty slices, and emits
     ONE epoch bump + delta + ``unnodes`` journal record.

Ticks ride the decision path (``Extender.handle`` calls
``maybe_tick`` under the decision lock, the checkpoint-cadence
pattern), so drains progress with traffic; the sim and the autoscaler
call ``tick()`` directly, which takes the decision lock itself.

On a sharded plane the replica being drained registers **drain
intent** with the ShardRouter so ``health_check()`` never dead-marks
it mid-choreography — eviction latency during a drain is expected,
not a liveness failure (the satellite race fix).

Nothing here is constructed unless ``drain_enabled``; the flag off
leaves placements, exposition, and journal bytes byte-identical.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

log = logging.getLogger("tpukube.drain")


class DrainCoordinator:
    """One per extender; owns every in-flight drain on this replica.

    Thread contract: mutations to cluster state run under the
    extender's decision lock (``tick``/``begin`` take it; ``maybe_tick``
    is called while it is already held — RLock). ``self._lock`` is a
    LEAF guarding only the drain records and counters; no state/gang
    call ever runs while holding it.
    """

    #: scheduling-clock seconds between amortized ticks
    TICK_INTERVAL_S = 0.5

    def __init__(self, extender, config) -> None:
        self.ext = extender
        self._config = config
        self._lock = threading.Lock()
        #: drain_id -> record (see begin())
        self._drains: dict[str, dict[str, Any]] = {}
        self._next_id = 0
        # the ShardRouter hook (satellite): set when this extender is
        # an in-process shard replica — drain intent keeps the health
        # checker from dead-marking the replica mid-choreography
        self._router = None
        self._router_idx: Optional[int] = None
        # counters (tpukube_drain_* series; rendered only when on)
        self.drains_started = 0
        self.drains_completed = 0
        self.evictions_total = 0
        self.nodes_removed_total = 0
        self.chips_removed_total = 0
        self.slices_dropped_total = 0
        #: disruption accounting: moves applied on the most recent
        #: tick, and the worst tick ever — scenario 15 asserts the
        #: peak never exceeds drain_max_concurrent_moves
        self.last_tick_moves = 0
        self.peak_tick_moves = 0
        self._last_tick = self.ext.clock.monotonic()

    # -- router intent (drain/health-check race fix) -----------------------
    def attach_router(self, router, idx: int) -> None:
        """Called by the ShardRouter when it builds in-process
        replicas: ``idx`` is this replica's shard index."""
        self._router = router
        self._router_idx = idx

    def _set_router_intent(self, active: bool) -> None:
        if self._router is None or self._router_idx is None:
            return
        try:
            if active:
                self._router.register_drain_intent(self._router_idx)
            else:
                self._router.clear_drain_intent(self._router_idx)
        except Exception:
            log.exception("drain intent update failed (replica %s)",
                          self._router_idx)

    # -- lifecycle ---------------------------------------------------------
    def begin(self, nodes, reason: str = "maintenance") -> str:
        """Start draining ``nodes``: cordon them (one seam), register
        router intent, and record the drain. Returns the drain id.
        Unknown names are ignored by the cordon; already-draining
        nodes simply join another drain's record too (idempotent —
        remove_nodes tolerates double removal)."""
        names = sorted(set(nodes))
        with self.ext._decision_lock:
            self.ext.state.set_cordon(names, True)
            # chip count up front (these nodes are leaving anyway, so
            # materializing a lazy view here costs nothing we keep)
            chips = 0
            for n in names:
                view = self.ext.state.node(n)
                if view is not None:
                    chips += len(view.info.chips)
            with self._lock:
                self._next_id += 1
                drain_id = f"drain-{self._next_id}"
                self._drains[drain_id] = {
                    "id": drain_id,
                    "nodes": set(names),
                    "reason": reason,
                    "chips": chips,
                    "started": self.ext.clock.monotonic(),
                    "evicted": 0,
                    "state": "draining",
                }
                self.drains_started += 1
            self._set_router_intent(True)
        if self.ext.journal is not None:
            # durability barrier on the cordon seam: a crash after
            # begin() returns must recover knowing WHICH capacity was
            # leaving — the maintenance intent outlives the process
            self.ext.journal.sync()
        self.ext._emit_event(
            "DrainStarted", f"drain/{drain_id}",
            f"draining {len(names)} node(s) ({chips} chips): {reason}",
            warning=False,
        )
        log.warning("drain %s: cordoned %d node(s) (%s)",
                    drain_id, len(names), reason)
        return drain_id

    def cancel(self, drain_id: str) -> bool:
        """Abort a drain: uncordon whatever of its nodes still exists.
        Evictions already applied stand (they were real releases)."""
        with self.ext._decision_lock:
            with self._lock:
                rec = self._drains.pop(drain_id, None)
            if rec is None:
                return False
            self.ext.state.set_cordon(sorted(rec["nodes"]), False)
            with self._lock:
                if not self._drains:
                    self._set_router_intent(False)
        self.ext._emit_event(
            "DrainCancelled", f"drain/{drain_id}",
            f"uncordoned {len(rec['nodes'])} node(s)",
        )
        return True

    def active(self) -> bool:
        with self._lock:
            return any(r["state"] == "draining"
                       for r in self._drains.values())

    # -- the choreography --------------------------------------------------
    def maybe_tick(self) -> None:
        """Amortized driver on the decision path (caller holds the
        decision lock): a clock read per decision, a real tick at
        TICK_INTERVAL_S cadence, nothing at all with no active drain."""
        if not self.active():
            return
        now = self.ext.clock.monotonic()
        if now - self._last_tick < self.TICK_INTERVAL_S:
            return
        self.tick()

    def tick(self) -> int:
        """One budgeted round of migrate-or-preempt across every
        active drain; drains whose nodes are empty complete (release +
        un-ingest). Returns workloads evicted this tick."""
        ext = self.ext
        with ext._decision_lock:
            self._last_tick = ext.clock.monotonic()
            with self._lock:
                draining = [r for r in self._drains.values()
                            if r["state"] == "draining"]
            if not draining:
                return 0
            all_nodes: set[str] = set()
            for rec in draining:
                all_nodes |= rec["nodes"]
            moves = self._evict_residents(all_nodes)
            with self._lock:
                self.last_tick_moves = moves
                self.peak_tick_moves = max(self.peak_tick_moves, moves)
            if moves == 0:
                # nothing left to move anywhere: complete every drain
                # whose nodes carry no live allocation
                self._complete_empty(draining)
            return moves

    def _evict_residents(self, nodes: set[str]) -> int:
        """Evict up to the disruption budget of resident workloads.
        Cheapest (lowest blocking priority) first — the same ordering
        preemption planning optimizes for; gang residents dissolve
        all-or-nothing through the shared victim machinery."""
        ext = self.ext
        node_of = {a.pod_key: a.node_name
                   for a in ext.state.allocations()}
        resident = []
        seen_gangs: set = set()
        for w in ext._preemption_workloads():
            if not any(node_of.get(pk) in nodes for pk in w.pod_keys):
                continue
            if w.gang_key is not None:
                # a DCN-split gang appears once per slice; evicting any
                # part dissolves the whole gang — budget it once
                if w.gang_key in seen_gangs:
                    continue
                seen_gangs.add(w.gang_key)
            resident.append(w)
        if not resident:
            return 0
        resident.sort(key=lambda w: (w.priority, w.id))
        budget = self._config.drain_max_concurrent_moves
        tenant_cap = self._config.drain_tenant_budget
        tenant_moved: dict[str, int] = {}
        moves = 0
        for w in resident:
            if moves >= budget:
                break
            if tenant_cap > 0 and w.tenant:
                if tenant_moved.get(w.tenant, 0) >= tenant_cap:
                    continue
            victim_pods = ext._victim_pod_keys([w])
            # provenance FIRST: _apply_victims notes "preempted" for
            # each pod; the drain stage names WHICH drain took the
            # chips (the explain chain the issue requires)
            for pk in sorted(victim_pods):
                node = node_of.get(pk)
                did = self._drain_of(node)
                ext._note_decision(pk, "drain_evict",
                                   drain=did, node=node)
            evicted, _held = ext._apply_victims([w])
            moves += 1
            if w.tenant:
                tenant_moved[w.tenant] = (
                    tenant_moved.get(w.tenant, 0) + 1)
            with self._lock:
                self.evictions_total += evicted
                for rec in self._drains.values():
                    if rec["state"] == "draining" and any(
                            node_of.get(pk) in rec["nodes"]
                            for pk in victim_pods):
                        rec["evicted"] += evicted
        return moves

    def _drain_of(self, node: Optional[str]) -> Optional[str]:
        if node is None:
            return None
        with self._lock:
            for rec in self._drains.values():
                if rec["state"] == "draining" and node in rec["nodes"]:
                    return rec["id"]
        return None

    def _complete_empty(self, draining: list[dict]) -> None:
        """Release + un-ingest every drain whose nodes hold no live
        allocation any more (caller holds the decision lock)."""
        ext = self.ext
        live = {a.node_name for a in ext.state.allocations()}
        for rec in draining:
            if rec["nodes"] & live:
                continue  # evictions still terminating
            out = ext.state.remove_nodes(sorted(rec["nodes"]))
            removed = out["removed"]
            with self._lock:
                rec["state"] = "completed"
                rec["removed"] = len(removed)
                rec["slices_dropped"] = out["slices_dropped"]
                rec["finished"] = ext.clock.monotonic()
                self.drains_completed += 1
                self.nodes_removed_total += len(removed)
                self.chips_removed_total += rec["chips"]
                self.slices_dropped_total += len(out["slices_dropped"])
                any_active = any(r["state"] == "draining"
                                 for r in self._drains.values())
            if not any_active:
                self._set_router_intent(False)
            if ext.journal is not None:
                # the decommission is reported complete only once the
                # un-ingest record is durable: losing it to a crash
                # would resurrect capacity the provider already took
                ext.journal.sync()
            ext._emit_event(
                "DrainCompleted", f"drain/{rec['id']}",
                f"un-ingested {len(removed)} node(s), dropped "
                f"slice(s) {out['slices_dropped']}, evicted "
                f"{rec['evicted']} pod(s)",
                warning=False,
            )
            log.warning(
                "drain %s complete: %d node(s) un-ingested, %d pod(s) "
                "evicted, slices dropped: %s", rec["id"], len(removed),
                rec["evicted"], out["slices_dropped"])

    # -- inspection --------------------------------------------------------
    def statusz(self) -> dict[str, Any]:
        """The /statusz "drain" section (rendered only when the flag
        is on — the extender adds the key conditionally)."""
        with self._lock:
            return {
                "started": self.drains_started,
                "completed": self.drains_completed,
                "evictions_total": self.evictions_total,
                "nodes_removed_total": self.nodes_removed_total,
                "chips_removed_total": self.chips_removed_total,
                "slices_dropped_total": self.slices_dropped_total,
                "peak_tick_moves": self.peak_tick_moves,
                "budget_moves": self._config.drain_max_concurrent_moves,
                "active": [
                    {
                        "id": r["id"],
                        "reason": r["reason"],
                        "nodes": len(r["nodes"]),
                        "chips": r["chips"],
                        "evicted": r["evicted"],
                    }
                    for r in sorted(self._drains.values(),
                                    key=lambda r: r["id"])
                    if r["state"] == "draining"
                ],
            }

    def stats(self) -> dict[str, int]:
        """Counter snapshot for the metrics renderer."""
        with self._lock:
            return {
                "started": self.drains_started,
                "completed": self.drains_completed,
                "evictions": self.evictions_total,
                "nodes_removed": self.nodes_removed_total,
                "chips_removed": self.chips_removed_total,
                "slices_dropped": self.slices_dropped_total,
                "peak_tick_moves": self.peak_tick_moves,
            }

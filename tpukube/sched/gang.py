"""Gang scheduling (SURVEY.md §2 C10, §9.3 "gang atomicity").

All-or-nothing placement of N-pod job groups onto one ICI-contiguous
sub-slice. The reference accumulates per-group reservations across
scheduling cycles; the last member's bind commits all, and a timeout rolls
all back. The TPU rendering:

  1. First member of a pod-group triggers a SLICE RESERVATION: slicefit
     finds a contiguous sub-box for the whole gang (min_member x chips/pod,
     honoring an optional shape hint) across the cluster mesh, spanning
     hosts. Reserved chips are invisible to non-gang placements.
  2. Members bind one by one; each takes chips from the reservation on its
     bound node. The min_member-th bind COMMITS the gang (reservation
     latency recorded — the north-star p50 gang-schedule metric).
  3. TTL expiry before quorum rolls EVERYTHING back: assigned members'
     allocations are released, the reservation dissolves — the "either
     fully lands or not at all" contract (BASELINE).
  4. A health fault on a reserved chip — or a downed ICI link between two
     reserved chips — before commit rolls the gang back (SURVEY.md §6:
     re-reserve a fresh contiguous slice); the next filter cycle
     re-reserves from scratch on healthy, fully-linked chips.

Linearizability: one lock orders all reservation mutations; binds
re-validate against the reservation under that lock (optimistic callers
simply retry the cycle, same as ledger bind races).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from tpukube.core.types import (
    Health,
    PodGroup,
    PodInfo,
    TopologyCoord,
)
from tpukube.obs.registry import Histogram
from tpukube.sched import slicefit
from tpukube.sched.snapshot import SnapshotCache, SnapshotDelta, sweep_for
from tpukube.sched.state import ClusterState, StateError

log = logging.getLogger("tpukube.gang")


class GangError(RuntimeError):
    pass


class NoSliceError(GangError):
    """No contiguous slice is free — the one GangError that may justify
    preemption. Configuration errors (shape/volume/chips-per-pod mismatch)
    must NOT trigger evictions."""


@dataclass
class GangReservation:
    """A gang's chip hold. Normally one contiguous box in one ICI slice;
    a gang that opted in to DCN spanning (``PodGroup.allow_dcn``, for
    data-parallel jobs whose gradient reduction tolerates DCN hops) may
    hold one contiguous sub-box in EACH of several slices. Members are
    whole within one slice either way (a pod's chips share a node)."""

    group: PodGroup
    namespace: str
    # slice id -> reserved chips in that slice (coords are slice-local)
    slice_coords: dict[str, set[TopologyCoord]]
    chips_per_pod: int
    priority: int = 0  # the reserving pods' priority (preemption blocking)
    # serving-plane tenant the reservation's chips are accounted to
    # ("" when tenancy is off — the TenantLedger never reads it then)
    tenant: str = ""
    created: float = field(default_factory=time.monotonic)
    # pod_key -> (slice id, that member's chips)
    assigned: dict[str, tuple[str, list[TopologyCoord]]] = field(
        default_factory=dict
    )
    # per-slice union of assigned coords, maintained by record_assignment/
    # drop_assignment (assigned_in runs per node per webhook — recomputing
    # the union there was measurable). Mutate assigned ONLY through those.
    _assigned_by_slice: dict[str, set[TopologyCoord]] = field(
        default_factory=dict
    )
    committed: bool = False
    commit_latency: Optional[float] = None
    # Two-phase preemption: the victim workloads this reservation plans to
    # evict, planned at /filter but EXECUTED only at the gang's first
    # /bind (extender._execute_pending_preemption). Until then the victims
    # keep running on the reserved chips; a reservation that TTLs out
    # unbound never evicts anyone. None once executed (or when the
    # reservation needed no preemption).
    pending_victims: Optional[list] = None
    # Executed-but-unconfirmed victims: a 2xx on the Eviction subresource
    # only STARTS graceful termination, so the victim physically holds its
    # chips until its pod object is gone. Member binds are gated on this
    # set being empty (extender.bind); the EvictionExecutor / lifecycle
    # watch clears entries through the recorded ``victim_gone`` decision.
    terminating_victims: set[str] = field(default_factory=set)

    def record_assignment(
        self, pod_key: str, slice_id: str, coords: list[TopologyCoord]
    ) -> None:
        self.assigned[pod_key] = (slice_id, list(coords))
        self._assigned_by_slice.setdefault(slice_id, set()).update(coords)

    def drop_assignment(self, pod_key: str) -> None:
        entry = self.assigned.pop(pod_key, None)
        if entry is not None:
            sid, coords = entry
            self._assigned_by_slice.get(sid, set()).difference_update(coords)

    @property
    def key(self) -> tuple[str, str]:
        return (self.namespace, self.group.name)

    @property
    def spans_dcn(self) -> bool:
        return len(self.slice_coords) > 1

    @property
    def slice_id(self) -> str:
        """The sole slice of an ICI-confined gang. DCN-spanning gangs have
        no single slice — callers there iterate ``slice_coords``."""
        if self.spans_dcn:
            raise GangError(
                f"gang {self.key} spans {len(self.slice_coords)} slices"
            )
        return next(iter(self.slice_coords))

    @property
    def coords(self) -> set[TopologyCoord]:
        """Sole slice's chips (single-slice gangs; see slice_id)."""
        return self.slice_coords[self.slice_id]

    def total_chips(self) -> int:
        return sum(len(cs) for cs in self.slice_coords.values())

    def assigned_in(self, slice_id: str) -> set[TopologyCoord]:
        return self._assigned_by_slice.get(slice_id, set())

    def unassigned_in(self, slice_id: str) -> set[TopologyCoord]:
        return self.slice_coords.get(slice_id, set()) - self.assigned_in(slice_id)

    # single-slice conveniences (tests + single-slice call sites)
    def assigned_coords(self) -> set[TopologyCoord]:
        return self.assigned_in(self.slice_id)

    def unassigned_coords(self) -> set[TopologyCoord]:
        return self.unassigned_in(self.slice_id)


class GangManager:
    """Owns all live reservations; consulted by the extender on every
    filter/prioritize/bind involving a gang pod, and by non-gang placement
    to mask reserved chips."""

    LATENCY_WINDOW = 4096

    def __init__(self, state: ClusterState, ttl_seconds: float = 30.0,
                 eviction_sink: Optional[deque] = None, events=None,
                 clock=None):
        from tpukube.core.clock import SYSTEM

        self._state = state
        self._ttl = ttl_seconds
        # scheduling-semantic time (reservation creation stamps, TTL
        # sweeps, commit-latency measurement against those stamps):
        # injectable for the discrete-event sim (core/clock.py)
        self._clock = clock if clock is not None else SYSTEM
        # structured event journal (obs/events.py), shared with the
        # owning Extender; None = no journal (standalone/unit tests)
        self._events = events
        self._lock = threading.RLock()
        self._reservations: dict[tuple[str, str], GangReservation] = {}
        # reservation-created -> committed durations (north-star p50 feed)
        self.commit_latencies: deque[float] = deque(maxlen=self.LATENCY_WINDOW)
        # same durations as monotonic histogram buckets (the _bucket
        # series on /metrics are counters: cumulative since process
        # start, never windowed — aggregatable across scrapes/instances)
        self.commit_hist = Histogram("gang_schedule_latency_seconds",
                                     bucket_only=True)
        self.rollbacks = 0  # TTL/fault rollbacks observed (metrics/tests)
        # Cluster-wide eviction bus, owned by the Extender (which also feeds
        # it preemption victims); gang rollback/dissolve appends rolled-back
        # members here (all-or-nothing: a half-gang must not keep running).
        self._evictions: deque[str] = (
            eviction_sink if eviction_sink is not None else deque()
        )
        # Evicted-but-still-terminating victims' chips: pod_key ->
        # (slice_id, coords). These chips are ledger-free (the eviction
        # released them) but PHYSICALLY held until the pod object is gone;
        # reserved_coords masks them so no bystander binds onto a chip a
        # terminating container still owns. Entries die on on_victim_gone
        # — independent of the reservation, which may roll back first.
        self._terminating_coords: dict[
            str, tuple[str, frozenset[TopologyCoord]]
        ] = {}
        # tenant resolver (pod -> tenant id), wired by the Extender
        # when the multi-tenant serving plane is on; None (the
        # default) stamps reservations with the empty tenant
        self.tenant_of = None
        # reservation epoch: bumped by every mutation of reservations,
        # assignments, or the terminating masks — the gang half of the
        # scheduling-snapshot cache key (sched/snapshot.py). A mutation
        # path that forgets to bump serves stale placements; the
        # invalidation tests cover every seam.
        self._epoch = 0
        # The epoch-cached scheduling snapshot, shared with the owning
        # Extender: filter/prioritize/preemption cycles and the metrics
        # /statusz renders all read ONE snapshot per epoch instead of
        # re-deriving grids from the ledger per call.
        self.snapshots = SnapshotCache(state, self)
        # wire both epoch owners' delta streams into the cache's log so
        # it can advance O(Δ) instead of rebuilding per epoch (a second
        # GangManager on the same state re-points the sink; the orphaned
        # cache then degrades to full rebuilds via log gaps — never to
        # a stale snapshot)
        state.set_delta_sink(self.snapshots)
        # durable-state journal (sched/journal.py), wired by the owning
        # Extender when journal_enabled; None journals nothing
        self._journal = None
        # gre records replayed with an unexecuted pending-victim plan:
        # finish_replay() drops whichever never saw their plan executed
        # (gvtaken) — their reserved box may overlap victims' chips and
        # the plan itself cannot round-trip the WAL
        self._replay_pending: set[tuple[str, str]] = set()

    def epoch(self) -> int:
        """Monotonic mutation counter (the snapshot cache's key half)."""
        with self._lock:
            return self._epoch

    def _note_delta_locked(self, slices=(), why: str = "") -> None:
        """Record the gang-epoch bump just taken (callers hold
        ``self._lock`` and call this right after ``self._epoch += 1``).
        Gang deltas carry only the TOUCHED slice ids: the reserved /
        terminating masks of those slices are re-read from this manager
        at apply time — they are O(Δ)-small and their union semantics
        (unassigned reservation chips ∪ terminating victims, which may
        overlap) already live in ``reserved_coords``."""
        self.snapshots.note(SnapshotDelta(
            kind="gang", epoch=self._epoch,
            slices=tuple(slices), why=why,
        ))

    def set_journal(self, journal) -> None:
        """Attach the durable-state journal (sched/journal.py); None
        detaches — recovery replays with the journal detached so the
        replayed mutations are not re-recorded."""
        with self._lock:
            self._journal = journal

    def _note_journal_locked(self, kind: str, data: dict) -> None:
        """Enqueue one gang-lifecycle WAL record (callers hold
        ``self._lock``; enqueue only — the journal's drain thread owns
        the file, so the gang lock never blocks on disk)."""
        journal = self._journal
        if journal is not None:
            journal.note(kind, data)

    @staticmethod
    def _res_doc(res: GangReservation) -> dict:
        """A reservation as a plain-JSON record (WAL ``gre`` payload and
        the Checkpoint's reservation list share this one shape)."""
        return {
            "ns": res.namespace,
            "g": {
                "n": res.group.name,
                "m": res.group.min_member,
                "shape": (list(res.group.shape)
                          if res.group.shape is not None else None),
                "dcn": res.group.allow_dcn,
            },
            "cpp": res.chips_per_pod,
            "prio": res.priority,
            "tenant": res.tenant,
            "committed": res.committed,
            # only the FLAG survives: a deferred (unexecuted) eviction
            # plan names live Workload objects that cannot round-trip —
            # recovery drops such reservations (the gang re-filters and
            # re-plans, exactly as after a legacy cold rebuild)
            "pv": bool(res.pending_victims),
            "sc": {sid: sorted([list(c) for c in coords])
                   for sid, coords in res.slice_coords.items()},
            "as": {pk: [sid, [list(c) for c in coords]]
                   for pk, (sid, coords) in res.assigned.items()},
            "tv": sorted(res.terminating_victims),
        }

    def _tenant_for(self, pod: PodInfo) -> str:
        """The reservation's tenant stamp; "" without a serving plane.
        A broken resolver must never fail a reservation."""
        if self.tenant_of is None:
            return ""
        try:
            return self.tenant_of(pod)
        except Exception:
            log.exception("tenant resolver failed for %s", pod.key())
            return ""

    def _emit(self, reason: str, res_key: tuple[str, str], message: str,
              warning: bool = False) -> None:
        """One journal event about a gang (no-op without a journal;
        never raises into the scheduling path)."""
        if self._events is None:
            return
        try:
            self._events.emit(
                reason, obj=f"gang/{res_key[0]}/{res_key[1]}",
                message=message,
                type="Warning" if warning else "Normal",
            )
        except Exception:
            log.exception("event emit failed: %s %s", reason, res_key)

    # -- views -------------------------------------------------------------
    def reservation(self, namespace: str, group_name: str) -> Optional[GangReservation]:
        with self._lock:
            return self._reservations.get((namespace, group_name))

    def reserved_coords(
        self, slice_id: Optional[str] = None
    ) -> set[TopologyCoord]:
        """Chips held for gang members that have not bound yet — masked out
        of every other placement. Coords are slice-local, so callers name
        the slice (None = all reservations, for single-slice callers).
        Assigned chips are NOT included: those live in the ledger as
        per-pod allocations already (state.commit runs before on_bound),
        and double-masking them would leak capacity after a committed
        gang's pods finish."""
        with self._lock:
            out: set[TopologyCoord] = set()
            for res in self._reservations.values():
                if slice_id is None:
                    for sid in res.slice_coords:
                        out |= res.unassigned_in(sid)
                else:
                    out |= res.unassigned_in(slice_id)
            # terminating victims' chips are ledger-free but physically
            # held: mask them exactly like unbound reservations
            for sid, coords in self._terminating_coords.values():
                if slice_id is None or sid == slice_id:
                    out |= coords
            return out

    # -- expiry / fault sweep ----------------------------------------------
    def sweep(self, now: Optional[float] = None) -> list[tuple[str, str]]:
        """Lazy janitor, called at the top of every gang interaction:
        rolls back (a) uncommitted reservations past TTL and (b) any
        uncommitted reservation whose slice lost a chip to a health fault
        or an internal ICI link to a link fault.
        Returns the rolled-back group keys."""
        now = self._clock.monotonic() if now is None else now
        rolled: list[tuple[str, str]] = []
        with self._lock:
            if all(r.committed for r in self._reservations.values()):
                # nothing sweepable (TTL/health/link rollback applies
                # only to UNCOMMITTED reservations, which the loop below
                # would skip anyway) — and this runs on every non-gang
                # filter, so skip the per-slice health/link snapshots
                return rolled
        # health/link state per slice from the epoch-cached snapshot
        # (this runs on every gang interaction; the direct accessors
        # re-scan every node view per call)
        snap = self.snapshots.current()
        unhealthy: dict[str, frozenset[TopologyCoord]] = {}
        broken: dict[str, frozenset] = {}
        for sid in snap.slice_ids():
            unhealthy[sid] = snap.slice(sid).unhealthy
            broken[sid] = snap.slice(sid).broken
        with self._lock:
            for key, res in list(self._reservations.items()):
                if res.committed:
                    continue
                # TTL-exempt while executed victims are still terminating:
                # those evictions are irreversible, so rolling the
                # reservation back would not un-evict anyone — it would
                # only let the gang re-reserve the victims' (ledger-free,
                # still physically held) chips and bind onto them, the
                # exact overlap the termination gate closes. The eviction
                # executor retries/confirms forever, so this state always
                # resolves (or pages the operator via /metrics).
                expired = (now - res.created > self._ttl
                           and not res.terminating_victims)
                sick = any(
                    coords & unhealthy.get(sid, set())
                    for sid, coords in res.slice_coords.items()
                )
                cut = any(
                    slicefit.coords_break_link(coords, broken.get(sid, set()))
                    for sid, coords in res.slice_coords.items()
                )
                if expired or sick or cut:
                    why = (
                        "TTL expired" if expired
                        else "chip fault in slice" if sick
                        else "ICI link fault in slice"
                    )
                    log.warning("gang %s/%s rollback: %s", key[0], key[1], why)
                    self._rollback_locked(res)
                    self._emit("GangRollback", key, why, warning=True)
                    rolled.append(key)
        return rolled

    def _evict_and_mask_locked(
        self, pod_key: str,
        entry: Optional[tuple[str, list[TopologyCoord]]],
    ) -> None:
        """The one way a gang layer eviction happens (rollback, dissolve,
        restore-rollback): release the ledger, queue the eviction for the
        executor, and mask the member's chips until the eviction is
        CONFIRMED. The pod may already be Running on its node — releasing
        the ledger alone would let another pod double-book those chips,
        and a rolled-back member terminates gracefully just like a
        preemption victim: a bystander bound onto its chip mid-grace
        would crash-loop on a single-owner TPU runtime. ``entry`` is the
        member's (slice, coords); None only when the coordinate space is
        genuinely unknown (restore with an unresolvable node on a
        multi-slice cluster) — then the mask is impossible and skipped."""
        self._state.release(pod_key)
        self._evictions.append(pod_key)
        if entry is not None and entry[1]:
            self._terminating_coords[pod_key] = (
                entry[0], frozenset(entry[1])
            )
        self._epoch += 1
        self._note_delta_locked(
            slices=(entry[0],) if entry is not None else (),
            why=f"evict+mask {pod_key}",
        )
        # WAL: the eviction INTENT plus the terminating mask — recovery
        # re-queues the eviction (if the pod still exists) so a
        # half-died gang finishes dying across a crash
        self._note_journal_locked("evict", {
            "p": pod_key,
            "sid": entry[0] if entry is not None else None,
            "c": ([list(c) for c in entry[1]]
                  if entry is not None else []),
        })

    def _rollback_locked(self, res: GangReservation) -> None:
        for pod_key in list(res.assigned):
            self._evict_and_mask_locked(pod_key, res.assigned.get(pod_key))
        self._reservations.pop(res.key, None)
        self._epoch += 1
        self._note_delta_locked(slices=res.slice_coords,
                                why=f"rollback {res.key}")
        self._note_journal_locked(
            "gdrop", {"ns": res.namespace, "g": res.group.name})
        self.rollbacks += 1

    # -- reservation -------------------------------------------------------
    def ensure_reservation(
        self, pod: PodInfo, chips_per_pod: int
    ) -> GangReservation:
        """Get or create the slice reservation for a gang pod's group.
        Raises GangError when no contiguous slice exists."""
        assert pod.group is not None
        self.sweep()
        with self._lock:
            key = (pod.namespace, pod.group.name)
            res = self._reservations.get(key)
            if res is not None:
                if res.chips_per_pod != chips_per_pod:
                    raise GangError(
                        f"gang {key}: member {pod.key()} wants {chips_per_pod} "
                        f"chips/pod but the reservation was made for "
                        f"{res.chips_per_pod}"
                    )
                return res
            slice_ids = self._state.slice_ids()
            if not slice_ids:
                raise GangError("no node topology known yet")
            total = pod.group.min_member * chips_per_pod
            if pod.group.shape is not None:
                sx, sy, sz = pod.group.shape
                if sx * sy * sz != total:
                    raise GangError(
                        f"gang {key}: shape {pod.group.shape} holds "
                        f"{sx * sy * sz} chips but the gang needs {total}"
                    )
            # A gang is ICI-contiguous, hence confined to ONE slice by
            # default (DCN crossings are the thing the scorer exists to
            # prevent). Slice choice bin-packs: the fullest slice that
            # still fits wins, so emptier slices stay whole for bigger
            # gangs. Deterministic tie-break on slice id.
            chosen: Optional[tuple[float, str, list[TopologyCoord]]] = None
            free_total = 0
            # one snapshot for the whole reservation cycle: the blocked
            # sweep (occupied | reserved, integral image prebuilt) is
            # shared with every other search of this epoch
            snap = self.snapshots.current()
            for sid in slice_ids:
                ss = snap.slice(sid)
                free_total += ss.blocked_free_chips
                coords = slicefit.find_slice_in(
                    ss.blocked_sweep(),
                    count=None if pod.group.shape is not None else total,
                    shape=pod.group.shape,
                    broken=ss.broken,
                )
                if coords is None:
                    continue
                rank = (-ss.utilization, sid)
                if chosen is None or rank < (chosen[0], chosen[1]):
                    chosen = (rank[0], sid, coords)
            if chosen is not None:
                _, sid, coords = chosen
                slice_coords = {sid: set(coords)}
            elif pod.group.allow_dcn and pod.group.shape is None:
                # DCN-spanning fallback (opt-in, DP-style jobs): one
                # contiguous sub-box per slice, every sub-box a multiple
                # of chips_per_pod so members stay slice-whole.
                slice_coords = self._plan_dcn_split(
                    total, chips_per_pod, slice_ids
                )
                if slice_coords is None:
                    raise NoSliceError(
                        f"gang {key}: {total} chips not coverable by "
                        f"per-slice contiguous boxes across "
                        f"{len(slice_ids)} ICI slices ({free_total} free)"
                    )
            else:
                raise NoSliceError(
                    f"gang {key}: no contiguous {total}-chip slice available "
                    f"in any of {len(slice_ids)} ICI slices "
                    f"({free_total} chips free)"
                )
            res = GangReservation(
                group=pod.group,
                namespace=pod.namespace,
                slice_coords=slice_coords,
                chips_per_pod=chips_per_pod,
                priority=pod.priority,
                tenant=self._tenant_for(pod),
                created=self._clock.monotonic(),
            )
            self._reservations[key] = res
            self._epoch += 1
            self._note_delta_locked(slices=slice_coords, why=f"reserve {key}")
            self._note_journal_locked("gre", self._res_doc(res))
            log.info(
                "gang %s/%s reserved %d chips over %d slice(s)",
                key[0], key[1], res.total_chips(), len(slice_coords),
            )
            self._emit(
                "GangReserved", key,
                f"{res.total_chips()} chips over "
                f"{len(slice_coords)} slice(s)",
            )
            return res

    def _plan_dcn_split(
        self, total: int, chips_per_pod: int, slice_ids: list[str]
    ) -> Optional[dict[str, set[TopologyCoord]]]:
        """Partition ``total`` chips into per-slice contiguous boxes, each a
        multiple of chips_per_pod. Greedy: slices in descending free
        capacity (tie: slice id), taking the largest box that fits the
        remaining need first — fewest DCN boundaries for the job, emptiest
        slices consumed first (the single-slice path already failed, so
        bin-packing has nothing left to protect)."""
        snap = self.snapshots.current()
        free_rank = sorted(
            slice_ids,
            key=lambda s: (snap.slice(s).utilization, s),
        )
        parts: dict[str, set[TopologyCoord]] = {}
        remaining = total
        for sid in free_rank:
            if remaining == 0:
                break
            ss = snap.slice(sid)
            # ONE box per slice — the TPU_KUBE_GANG_* contract promises the
            # in-pod runtime one contiguous ICI sub-mesh per slice part
            free_here = ss.blocked_free_chips
            vol = min(remaining, (free_here // chips_per_pod) * chips_per_pod)
            while vol >= chips_per_pod:
                coords = slicefit.find_slice_in(
                    ss.blocked_sweep(), count=vol, broken=ss.broken
                )
                if coords is not None:
                    parts[sid] = set(coords)
                    remaining -= len(coords)
                    break
                vol -= chips_per_pod
        return parts if remaining == 0 else None

    def snapshot(self) -> list[GangReservation]:
        """Stable copy of live reservations (the preemption planner's view)."""
        with self._lock:
            return list(self._reservations.values())

    def dissolve(self, key: tuple[str, str]) -> list[str]:
        """Evict a whole gang (preemption victim): release every member's
        allocation, queue their evictions, drop the reservation. Gangs die
        all-or-nothing exactly as they are born. Returns evicted pod keys."""
        with self._lock:
            # look up before popping: the no-such-gang path mutates
            # nothing and owes no epoch bump (epoch-discipline lint)
            res = self._reservations.get(key)
            if res is None:
                return []
            self._reservations.pop(key, None)
            self._epoch += 1
            self._note_delta_locked(slices=res.slice_coords,
                                    why=f"dissolve {key}")
            self._note_journal_locked("gdrop", {"ns": key[0], "g": key[1]})
            evicted = []
            for pod_key in list(res.assigned):
                self._evict_and_mask_locked(pod_key,
                                            res.assigned.get(pod_key))
                evicted.append(pod_key)
            log.warning(
                "gang %s/%s dissolved by preemption (%d members evicted)",
                key[0], key[1], len(evicted),
            )
            self._emit(
                "GangDissolved", key,
                f"preempted; {len(evicted)} member(s) evicted",
                warning=True,
            )
            return evicted

    def restore(
        self, namespace: str, group: PodGroup, allocs: list
    ) -> Optional[GangReservation]:
        """Rebuild a gang's reservation from its members' restored
        allocations after an extender restart (the extender's
        rebuild_from_pods). Without this, running gang members look like
        free-standing pods to the preemption planner and could be evicted
        individually — partial gang death. ``allocs`` are the members'
        AllocResults (already committed to the ledger).

        A quorum of members means the gang had committed: restore it as
        committed with exactly its members' chips. A partial set (restart
        mid-assembly) lost its in-memory unassigned-chip pool, so re-derive
        it: find a full-size free box CONTAINING the members' chips; if none
        exists the gang can never complete — roll it back now (members
        released + queued for eviction), all-or-nothing in death as in
        birth, rather than letting late members bind as strays."""
        with self._lock:
            key = (namespace, group.name)
            if key in self._reservations or not allocs:
                return self._reservations.get(key)
            chips_per_pod = max(1, len(allocs[0].coords))
            member_slices: dict[str, str] = {}

            def rollback_all(why: str) -> None:
                log.warning("gang %s/%s: %s — rolling back",
                            namespace, group.name, why)
                self._emit("GangRollback", key, why, warning=True)
                for a in allocs:
                    # restored members may be RUNNING: mask their chips
                    # until the eviction confirms. Prefer the resolved
                    # member_slices entry (it carries the single-slice
                    # fallback for nodes whose view is gone); only a
                    # multi-slice cluster with an unresolvable node
                    # leaves the coordinate space unknown (mask skipped).
                    sid = member_slices.get(a.pod_key)
                    if sid is None:
                        sid = self._state.slice_of_node(a.node_name)
                    entry = (
                        (sid, [TopologyCoord.of(c) for c in a.coords])
                        if sid is not None else None
                    )
                    self._evict_and_mask_locked(a.pod_key, entry)
                self.rollbacks += 1

            # the members' nodes know which ICI slice(s) the gang lives in;
            # with a node view gone, only an unambiguous (single-slice)
            # cluster lets us proceed — guessing would mix coord spaces
            for a in allocs:
                sid = self._state.slice_of_node(a.node_name)
                if sid is None:
                    sids = self._state.slice_ids()
                    if len(sids) != 1:
                        rollback_all(
                            f"member node {a.node_name} unknown and cluster "
                            f"has {len(sids)} slices"
                        )
                        return None
                    sid = sids[0]  # guard above guarantees exactly one
                member_slices[a.pod_key] = sid
            committed = len(allocs) >= group.min_member
            by_slice: dict[str, set[TopologyCoord]] = {}
            for a in allocs:
                by_slice.setdefault(member_slices[a.pod_key], set()).update(
                    a.coords
                )
            if len(by_slice) > 1:
                # DCN-spanning gang: committed restores with exactly the
                # members' chips; mid-assembly the split plan is gone and
                # not re-derivable (which sub-box was whose?) — roll back
                if not committed:
                    rollback_all(
                        f"restart found {len(allocs)}/{group.min_member} "
                        f"members of a DCN-spanning gang"
                    )
                    return None
                slice_coords = by_slice
            else:
                slice_id = next(iter(by_slice))
                coords = set(by_slice[slice_id])
                if not committed:
                    coords_or_none = self._recomplete_slice(
                        group, chips_per_pod, coords, slice_id
                    )
                    if coords_or_none is None:
                        rollback_all(
                            f"restart found {len(allocs)}/{group.min_member} "
                            f"members and no completable slice"
                        )
                        return None
                    coords = coords_or_none
                slice_coords = {slice_id: coords}
            from tpukube.device.tpu import ENV_KUBE_TENANT

            res = GangReservation(
                group=group,
                namespace=namespace,
                slice_coords=slice_coords,
                chips_per_pod=chips_per_pod,
                priority=max(a.priority for a in allocs),
                # tenant attribution survives the restart through the
                # members' alloc-annotation env, like the chips do
                tenant=next(
                    (a.env.get(ENV_KUBE_TENANT) for a in allocs
                     if a.env.get(ENV_KUBE_TENANT)), "",
                ),
                created=self._clock.monotonic(),
            )
            for a in allocs:
                res.record_assignment(
                    a.pod_key, member_slices[a.pod_key], list(a.coords)
                )
            res.committed = committed
            self._reservations[key] = res
            self._epoch += 1
            self._note_delta_locked(slices=slice_coords, why=f"restore {key}")
            self._note_journal_locked("gre", self._res_doc(res))
            log.info(
                "gang %s/%s restored from pod annotations: %d members, "
                "committed=%s", namespace, group.name, len(res.assigned),
                res.committed,
            )
            return res

    def _recomplete_slice(
        self,
        group: PodGroup,
        chips_per_pod: int,
        assigned: set[TopologyCoord],
        slice_id: str,
    ) -> Optional[set[TopologyCoord]]:
        """Full-size contiguous box containing ``assigned``, treating the
        members' own chips as free (they are the gang's). None if the mesh
        is unknown or no such box exists."""
        try:
            mesh = self._state.slice_mesh(slice_id)
        except StateError:
            return None
        total = group.min_member * chips_per_pod
        shape = group.shape
        if shape is not None and shape[0] * shape[1] * shape[2] != total:
            shape = None  # malformed hint: fall back to count search
        snap = self.snapshots.current()
        ss = snap.slice(slice_id)
        # members-look-free is request-specific: an ad-hoc sweep (via the
        # snapshot module's sole constructor seam), not the cached one
        # absent stays blocked even where a member was assigned: a chip
        # whose host left cannot be restored onto
        occupied = ((ss.occupied | ss.reserved) - assigned) | ss.absent
        sweep = sweep_for(mesh, occupied)
        best: Optional[tuple] = None
        for sb in slicefit.iter_free_boxes_in(
            sweep,
            count=total if shape is None else None,
            shape=shape,
            broken=ss.broken,
        ):
            box_set = set(slicefit.box_coords(mesh, sb.box))
            if assigned <= box_set and (
                best is None or sb.sort_key < best[0]
            ):
                best = (sb.sort_key, box_set)
        return best[1] if best is not None else None

    def reserve_exact(
        self, pod: PodInfo, chips_per_pod: int, coords: list[TopologyCoord],
        slice_id: str, pending_victims: Optional[list] = None,
    ) -> GangReservation:
        """Reserve a specific chip set (the preemption path: policy already
        chose the box and its victims). ``pending_victims`` defers the
        evictions to the gang's first bind (two-phase preemption). Raises
        if any non-victim chip was taken since planning — the scheduler
        retries."""
        return self.reserve_exact_split(
            pod, chips_per_pod, {slice_id: list(coords)},
            pending_victims=pending_victims,
        )

    def reserve_exact_split(
        self, pod: PodInfo, chips_per_pod: int,
        parts: dict[str, list[TopologyCoord]],
        pending_victims: Optional[list] = None,
    ) -> GangReservation:
        """Reserve specific per-slice chip sets (single- or multi-slice
        preemption). ``pending_victims`` (policy.Workload list) records the
        eviction plan WITHOUT executing it: their chips may legitimately
        still be occupied, and stay so until the gang's first bind. Raises
        if any chip outside the victim set is occupied — the scheduler
        retries."""
        assert pod.group is not None
        victim_held: dict[str, set[TopologyCoord]] = {}
        for w in pending_victims or ():
            victim_held.setdefault(w.slice_id, set()).update(w.coords)
        with self._lock:
            key = (pod.namespace, pod.group.name)
            existing = self._reservations.get(key)
            if existing is not None:
                return existing  # lost a benign race with a sibling member
            expected = pod.group.min_member * chips_per_pod
            got = sum(len(cs) for cs in parts.values())
            if got != expected:
                raise GangError(
                    f"gang {key}: preemption opened {got} chips but "
                    f"the gang needs {expected}"
                )
            victim_gangs = {
                w.gang_key for w in pending_victims or () if w.gang_key
            }
            snap = self.snapshots.current()
            for slice_id, coords in parts.items():
                try:
                    ss = snap.slice(slice_id)
                except KeyError:
                    raise GangError(
                        f"gang {key}: unknown slice {slice_id!r}"
                    ) from None
                # victim-held chips may legitimately still be OCCUPIED
                # (their eviction is deferred), but another reservation's
                # coords always clash — only reservations that are
                # themselves declared victims (dissolved at execution)
                # are exempt
                reserved: set[TopologyCoord] = set()
                for other in self._reservations.values():
                    if other.key not in victim_gangs:
                        reserved |= other.unassigned_in(slice_id)
                occupied = (
                    ss.occupied - victim_held.get(slice_id, set())
                ) | reserved
                # terminating victims' chips are ledger-free (their
                # eviction already released them) but physically held
                # until the pod object is gone — a preemption-opened box
                # overlapping them would bind members onto chips a dying
                # container still owns, with zero victims to gate on
                occupied |= ss.terminating
                clash = [c for c in coords if c in occupied]
                if clash:
                    raise GangError(
                        f"gang {key}: preempted box re-occupied at "
                        f"{clash[:3]} in {slice_id}; retry"
                    )
                if slicefit.coords_break_link(set(coords), ss.broken):
                    raise GangError(
                        f"gang {key}: preempted box in {slice_id} spans a "
                        f"downed ICI link; retry"
                    )
            res = GangReservation(
                group=pod.group,
                namespace=pod.namespace,
                slice_coords={s: set(cs) for s, cs in parts.items()},
                chips_per_pod=chips_per_pod,
                priority=pod.priority,
                tenant=self._tenant_for(pod),
                created=self._clock.monotonic(),
                pending_victims=(
                    list(pending_victims) if pending_victims else None
                ),
            )
            self._reservations[key] = res
            self._epoch += 1
            self._note_delta_locked(slices=parts, why=f"reserve-exact {key}")
            self._note_journal_locked("gre", self._res_doc(res))
            log.info(
                "gang %s/%s reserved %d chips over %d slice(s) via preemption"
                " (%d victim workload(s) pending first bind)",
                key[0], key[1], res.total_chips(), len(parts),
                len(pending_victims or ()),
            )
            self._emit(
                "GangReserved", key,
                f"{res.total_chips()} chips over {len(parts)} slice(s) "
                f"via preemption "
                f"({len(pending_victims or ())} victim(s) pending)",
            )
            return res

    def peek_pending_victims(self, res: GangReservation) -> list:
        """The deferred eviction plan, without claiming it (the extender
        pre-validates the bind against it before executing)."""
        with self._lock:
            if self._reservations.get(res.key) is not res:
                return []
            return list(res.pending_victims or [])

    def take_pending_victims(self, res: GangReservation) -> list:
        """Atomically claim a reservation's deferred eviction plan (empty
        if already executed, or if the reservation was replaced). The
        caller — extender bind, under the decision lock — executes it."""
        with self._lock:
            if self._reservations.get(res.key) is not res:
                return []
            victims = res.pending_victims or []
            res.pending_victims = None
            if victims:
                # WAL: the deferred plan is now EXECUTING — a recovery
                # no longer drops this reservation as plan-lost
                self._note_journal_locked(
                    "gvtaken", {"ns": res.namespace, "g": res.group.name})
            return list(victims)

    def register_terminating(
        self, res: GangReservation,
        held: dict[str, tuple[str, list[TopologyCoord]]],
    ) -> None:
        """Record executed evictions awaiting confirmed termination:
        ``held`` maps each evicted pod to the (slice, coords) its
        containers still physically hold. Gates the gang's member binds
        AND masks the chips from every other placement until
        on_victim_gone confirms the pod object is gone."""
        if not held:
            return
        with self._lock:
            for pod_key, (sid, coords) in held.items():
                res.terminating_victims.add(pod_key)
                if coords:
                    self._terminating_coords[pod_key] = (
                        sid, frozenset(coords)
                    )
            self._epoch += 1
            self._note_delta_locked(
                slices={sid for sid, _ in held.values()},
                why=f"register-terminating {res.key}",
            )
            self._note_journal_locked("gterm", {
                "ns": res.namespace, "g": res.group.name,
                "pods": {pk: [sid, [list(c) for c in coords]]
                         for pk, (sid, coords) in held.items()},
            })

    def on_victim_gone(self, pod_key: str) -> bool:
        """A terminating eviction victim's pod object is confirmed gone
        (EvictionExecutor / lifecycle watch, via the recorded
        ``victim_gone`` decision): unmask its chips and unblock any gang
        waiting on it. Returns True if anything was tracking the pod."""
        with self._lock:
            # membership first, pop only on a hit: the unknown-pod path
            # mutates nothing and owes no bump (epoch-discipline lint)
            entry = self._terminating_coords.get(pod_key)
            hit = entry is not None
            gated = False
            for res in self._reservations.values():
                if pod_key in res.terminating_victims:
                    res.terminating_victims.discard(pod_key)
                    gated = True
                    if not res.terminating_victims:
                        log.info(
                            "gang %s/%s: all preemption victims terminated; "
                            "member binds may proceed",
                            res.namespace, res.group.name,
                        )
            if not hit and not gated:
                return False
            if hit:
                self._terminating_coords.pop(pod_key, None)
                if self._events is not None:
                    try:
                        self._events.emit(
                            "VictimGone", obj=f"pod/{pod_key}",
                            message="eviction victim's pod object "
                                    "confirmed gone; its chips are "
                                    "placeable again",
                        )
                    except Exception:
                        log.exception("event emit failed: VictimGone %s",
                                      pod_key)
                # the unmasked chips are placeable again: invalidate
                self._epoch += 1
                self._note_delta_locked(slices=(entry[0],),
                                        why=f"victim-gone {pod_key}")
            # WAL: ONE record covers both the coord unmask and the
            # bind-gate clear (a reservation can gate on a victim whose
            # alloc carried no coords — the record must still replay).
            # The single unconditional site at the region tail is what
            # lets the seam-triple pass PROVE every bump path journals
            # without value-tracking `hit`.
            self._note_journal_locked("gvgone", {"p": pod_key})
            return True

    def terminating_victims_of(self, res: GangReservation) -> set[str]:
        """Victims whose termination still gates this gang's binds."""
        with self._lock:
            return set(res.terminating_victims)

    def terminating_count(self) -> int:
        """Evicted-but-unconfirmed victims cluster-wide (metrics)."""
        with self._lock:
            return len(self._terminating_coords)

    def terminating_pod_keys(self) -> list[str]:
        """Every pod key any terminating bookkeeping still tracks —
        coord masks AND reservation bind gates (recovery prunes the
        ones whose pod objects no longer exist, since their confirm
        channel died with the crashed process)."""
        with self._lock:
            keys = set(self._terminating_coords)
            for res in self._reservations.values():
                keys |= res.terminating_victims
            return sorted(keys)

    def terminating_coords(self, slice_id: str) -> set[TopologyCoord]:
        """Chips of evicted-but-still-terminating victims in one slice.
        They are ledger-free and reservation-free but PHYSICALLY held, so
        the preemption planner must treat them exactly like unhealthy
        chips: no eviction can free them any sooner, and a plan that
        reserves them reopens the double-ownership window the
        termination gate closes (ADVICE round 5 medium)."""
        with self._lock:
            out: set[TopologyCoord] = set()
            for sid, coords in self._terminating_coords.values():
                if sid == slice_id:
                    out |= coords
            return out

    # -- per-node queries for the extender ----------------------------------
    def _node_slice(
        self, res: GangReservation, node_name: str
    ) -> Optional[str]:
        """Which of the reservation's slices this node belongs to (None if
        the gang holds nothing in the node's ICI domain)."""
        sid = self._state.slice_of_node(node_name)
        return sid if sid in res.slice_coords else None

    def node_availability(
        self, res: GangReservation
    ) -> dict[str, tuple[int, int]]:
        """Per-node (unassigned, total) reserved-chip counts in ONE pass
        over the reservation. filter/prioritize call this once per
        webhook and answer every node from it — the per-node coord scan
        (O(nodes x reserved chips) per webhook) was the hottest
        app-level term in the 64-member gang-commit profile."""
        snapshots = {
            sid: self._state.hosts_by_coord(sid) for sid in res.slice_coords
        }
        out: dict[str, list[int]] = {}
        with self._lock:
            for sid, coords in res.slice_coords.items():
                hosts = snapshots[sid]
                unassigned = res.unassigned_in(sid)
                for c in coords:
                    h = hosts.get(c)
                    if h is None:
                        continue
                    entry = out.setdefault(h, [0, 0])
                    entry[1] += 1
                    if c in unassigned:
                        entry[0] += 1
        return {h: (a, t) for h, (a, t) in out.items()}

    def feasibility_from(
        self, counts: dict[str, tuple[int, int]], res: GangReservation,
        node_name: str,
    ) -> Optional[str]:
        """node_feasibility answered from a node_availability snapshot.

        A node absent from the snapshot hosts NONE of the reservation's
        coords. When the node's whole ICI slice is outside the
        reservation — the commonest infeasible case — report the
        historical no-chips-in-slice reason instead of a misleading
        '0 unassigned chips here' (ADVICE round 5 low); an in-slice node
        that merely hosts none of the reserved chips keeps the counted
        message."""
        entry = counts.get(node_name)
        if entry is None:
            if self._node_slice(res, node_name) is None:
                return "gang holds no chips in this node's ICI slice"
            entry = (0, 0)
        avail = entry[0]
        if avail < res.chips_per_pod:
            return (
                f"gang slice has {avail} unassigned chips here, "
                f"pod needs {res.chips_per_pod}"
            )
        return None

    @staticmethod
    def score_from(
        counts: dict[str, tuple[int, int]], node_name: str
    ) -> int:
        """node_score from a node_availability snapshot: more unassigned
        reserved chips on the node = higher score — fill the slice host
        by host so members land dense, not scattered."""
        avail, total = counts.get(node_name, (0, 0))
        return round(10 * avail / total) if total else 0


    def plan_for_bind(
        self, res: GangReservation, pod: PodInfo, node_name: str
    ) -> list[TopologyCoord]:
        """Pick this member's chips from the reservation on its node,
        preferring chips adjacent to already-assigned ones (members that
        talk most ride the shortest ICI paths)."""
        sid = self._node_slice(res, node_name)
        if sid is None:
            raise GangError(
                f"gang {res.key}: no reserved chips in {node_name}'s slice"
            )
        mesh = self._state.slice_mesh(sid)
        hosts = self._state.hosts_by_coord(sid)
        with self._lock:
            if res.key not in self._reservations:
                raise GangError(f"gang {res.key}: reservation dissolved; retry")
            if pod.key() in res.assigned:
                raise GangError(f"{pod.key()} already assigned in gang")
            avail = sorted(
                c for c in res.unassigned_in(sid)
                if hosts.get(c) == node_name
            )
            if len(avail) < res.chips_per_pod:
                raise GangError(
                    f"gang {res.key}: node {node_name} no longer has "
                    f"{res.chips_per_pod} unassigned slice chips"
                )
            anchor = res.assigned_in(sid)
            chosen: list[TopologyCoord] = []
            pool = list(avail)
            for _ in range(res.chips_per_pod):
                best = max(
                    pool,
                    key=lambda c: (
                        sum(1 for nb in mesh.neighbors(c) if nb in anchor or nb in chosen),
                        tuple(-v for v in c),
                    ),
                )
                chosen.append(best)
                pool.remove(best)
            return chosen

    def on_bound(self, res: GangReservation, pod_key: str,
                 coords: list[TopologyCoord], node_name: str) -> bool:
        """Record a member's successful ledger commit; the quorum member
        commits the whole gang. Returns True when THIS bind triggered the
        commit — the caller needs it to undo truthfully if its external
        bind effector subsequently fails (undo_commit)."""
        sid = self._node_slice(res, node_name)
        if sid is None:
            raise GangError(
                f"gang {res.key}: bound node {node_name} is outside the "
                f"reservation's slices"
            )
        with self._lock:
            live = self._reservations.get(res.key)
            if live is not res:
                raise GangError(f"gang {res.key}: reservation replaced mid-bind")
            bad = [c for c in coords if c not in res.unassigned_in(sid)]
            if bad:
                raise GangError(f"gang {res.key}: coords {bad} not reservable")
            res.record_assignment(pod_key, sid, list(coords))
            self._epoch += 1
            self._note_delta_locked(slices=(sid,), why=f"bound {pod_key}")
            self._note_journal_locked("gbound", {
                "ns": res.namespace, "g": res.group.name, "p": pod_key,
                "sid": sid, "c": [list(c) for c in coords],
            })
            if not res.committed and len(res.assigned) >= res.group.min_member:
                res.committed = True
                res.commit_latency = self._clock.monotonic() - res.created
                self.commit_latencies.append(res.commit_latency)
                self.commit_hist.observe(res.commit_latency)
                log.info(
                    "gang %s/%s COMMITTED: %d members in %.3fs",
                    res.namespace, res.group.name,
                    len(res.assigned), res.commit_latency,
                )
                self._emit(
                    "GangCommitted", res.key,
                    f"{len(res.assigned)} members in "
                    f"{res.commit_latency:.3f}s",
                )
                return True
        return False

    def undo_commit(self, res: GangReservation) -> None:
        """Revert a commit whose triggering bind failed at the apiserver:
        the quorum never truly assembled, so the committed flag (which
        exempts the reservation from the TTL/health sweep) and the
        recorded north-star latency sample must both go — otherwise a
        failing apiserver leaves a committed-below-quorum reservation
        masking chips forever and a latency sample for a commit that
        never happened."""
        with self._lock:
            if not res.committed:
                return
            res.committed = False
            self._note_journal_locked(
                "guncommit", {"ns": res.namespace, "g": res.group.name})
            try:
                # remove by value, not tail position: the effector runs
                # outside the decision lock, so another gang's commit can
                # land between this gang's commit and its undo
                self.commit_latencies.remove(res.commit_latency)
            except ValueError:
                pass  # window overflow evicted it already
            # commit_hist keeps its sample: _bucket series are monotonic
            # counters and cannot un-count — one phantom observation on
            # this rare apiserver-failure path beats a counter decrease
            # (which Prometheus would read as a process restart)
            log.warning(
                "gang %s/%s commit UNDONE (quorum bind failed at the "
                "apiserver)", res.namespace, res.group.name,
            )

    # -- pod lifecycle -------------------------------------------------------
    def assignable(self, res: GangReservation, chips_per_pod: int) -> bool:
        """True while the reservation still has room for another member —
        room in SOME one slice (a member's chips never straddle slices).
        Replicas beyond min_member of a committed gang get False — they
        fall through to normal (non-gang) scheduling in the extender."""
        with self._lock:
            return any(
                len(res.unassigned_in(sid)) >= chips_per_pod
                for sid in res.slice_coords
            )

    def on_release(self, pod_key: str) -> None:
        """A gang member's pod went away. Uncommitted gang: the chips return
        to the reservation pool (a replacement member can take them).
        Committed gang: ditto while other members live; when the LAST member
        of a committed gang is released the reservation dissolves — keeping
        it would mask the freed chips forever (capacity leak)."""
        with self._lock:
            for res in self._reservations.values():
                if pod_key in res.assigned:
                    sid = res.assigned[pod_key][0]
                    res.drop_assignment(pod_key)
                    if res.committed and not res.assigned:
                        self._reservations.pop(res.key, None)
                        log.info(
                            "gang %s/%s dissolved (all members released)",
                            res.namespace, res.group.name,
                        )
                    # one bump AFTER the last seam of the batch (the
                    # epoch-discipline lint checks bump-follows-seam)
                    self._epoch += 1
                    self._note_delta_locked(
                        slices=(sid,), why=f"member-release {pod_key}")
                    self._note_journal_locked("gmrel", {"p": pod_key})
                    return

    def reassign(self, pod_key: str, coords: list[TopologyCoord]) -> bool:
        """Repoint a bound member's recorded chips (device-id reconcile:
        the kubelet allocated different chips than planned — the ledger
        already follows reality; gang bookkeeping must too, or released
        members would free the WRONG coords back into the pool). The
        reservation's chip pool moves with it: the abandoned planned
        coords leave slice_coords (they are ledger-free — keeping them
        'reserved but unassigned' would mask free chips forever and
        re-open assignable()), and the actual coords join it."""
        with self._lock:
            for res in self._reservations.values():
                entry = res.assigned.get(pod_key)
                if entry is not None:
                    sid, old = entry
                    res.drop_assignment(pod_key)
                    pool = res.slice_coords.get(sid, set())
                    pool.difference_update(old)
                    pool.update(coords)
                    res.slice_coords[sid] = pool
                    res.record_assignment(pod_key, sid, list(coords))
                    self._epoch += 1
                    # net reserved change is empty (old coords leave
                    # pool+assigned, new join both), but the note keeps
                    # the delta chain contiguous for this bump
                    self._note_delta_locked(
                        slices=(sid,), why=f"reassign {pod_key}")
                    self._note_journal_locked("greas", {
                        "p": pod_key, "c": [list(c) for c in coords],
                    })
                    return True
        return False

    # -- durable-state checkpoint + WAL replay (sched/journal.py) ------------
    def checkpoint_doc(self) -> dict:
        """Reservations + terminating masks as a plain-JSON Checkpoint
        fragment (in-memory only; the journal's drain thread owns the
        serialization and the disk)."""
        with self._lock:
            return {
                "epoch": self._epoch,
                "res": [self._res_doc(r)
                        for r in self._reservations.values()],
                "term": {pk: [sid, sorted(list(c) for c in coords)]
                         for pk, (sid, coords)
                         in self._terminating_coords.items()},
            }

    def _res_from_doc_locked(self, doc: dict) -> GangReservation:
        """Rebuild a GangReservation from a ``_res_doc`` record
        (callers hold ``self._lock`` and register the result; the
        member re-assignment is a seam event, so this helper owns an
        epoch bump of its own — the callers' registration bump then
        covers the reservation map write). The created stamp is NOW
        (fresh TTL — exactly the grace a legacy restore grants); an
        unexecuted eviction plan never round-trips (see ``_res_doc``)."""
        g = doc["g"]
        group = PodGroup(
            name=g["n"], min_member=int(g["m"]),
            shape=(tuple(int(v) for v in g["shape"])
                   if g.get("shape") else None),
            allow_dcn=bool(g.get("dcn")),
        )
        res = GangReservation(
            group=group,
            namespace=doc["ns"],
            slice_coords={
                sid: {TopologyCoord(*c) for c in coords}
                for sid, coords in doc["sc"].items()
            },
            chips_per_pod=int(doc["cpp"]),
            priority=int(doc["prio"]),
            tenant=doc.get("tenant", ""),
            created=self._clock.monotonic(),
        )
        for pk, entry in doc.get("as", {}).items():
            res.record_assignment(
                pk, entry[0], [TopologyCoord(*c) for c in entry[1]]
            )
        res.committed = bool(doc.get("committed"))
        for pk in doc.get("tv", ()):
            res.terminating_victims.add(pk)
        self._epoch += 1
        # without the delta note this bump is a GAP in the contiguous
        # delta chain: the first post-replay lookup (and every replayed
        # `gre` record after it) would fall off the O(Δ) advance into a
        # full O(chips) rebuild — found by tpukube-lint's seam-triple
        # pass (journal-exempt: this IS replay; noting would re-record)
        self._note_delta_locked(slices=res.slice_coords,
                                why=f"replayed reservation {res.key}")
        return res

    def restore_checkpoint(self, doc: dict) -> int:
        """Rebuild reservations and terminating masks VERBATIM from a
        Checkpoint fragment onto a fresh manager (recovery's warm
        path). A reservation checkpointed with an UNEXECUTED deferred
        preemption plan and no bound members is dropped: the plan's
        victim workloads cannot round-trip, so restoring the box would
        hold chips no bind can ever open — the gang simply re-filters
        and re-plans, exactly as after a legacy cold rebuild. Returns
        reservations restored."""
        restored = 0
        with self._lock:
            self._epoch = int(doc.get("epoch", 0))
            touched: set[str] = set()
            for rd in doc.get("res", ()):
                if rd.get("pv") and not rd.get("as"):
                    log.warning(
                        "checkpoint restore: dropping reservation %s/%s "
                        "with an unexecuted preemption plan (the plan "
                        "does not survive a crash; the gang re-plans)",
                        rd["ns"], rd["g"]["n"],
                    )
                    continue
                res = self._res_from_doc_locked(rd)
                self._reservations[res.key] = res
                touched.update(res.slice_coords)
                restored += 1
            for pk, entry in doc.get("term", {}).items():
                self._terminating_coords[pk] = (
                    entry[0],
                    frozenset(TopologyCoord(*c) for c in entry[1]),
                )
                touched.add(entry[0])
            self._epoch += 1
            self._note_delta_locked(slices=touched,
                                    why="checkpoint restore")
        return restored

    def apply_journal(self, rec: dict) -> None:
        """Apply one replayed gang-lifecycle WAL record (recovery path,
        journal detached). Mirrors the live mutators MINUS their side
        channels: no events, no latency samples, and no cascading
        ledger releases — those have their own WAL records in the
        stream, in order."""
        kind, d = rec["k"], rec["d"]
        with self._lock:
            if kind == "gre":
                res = self._res_from_doc_locked(d)
                self._reservations[res.key] = res
                if d.get("pv"):
                    # deferred plan lost across the crash: candidate for
                    # the finish_replay() drop unless a gvtaken record
                    # later proves the plan executed
                    self._replay_pending.add(res.key)
                self._epoch += 1
                self._note_delta_locked(slices=res.slice_coords,
                                        why=f"replay gre {res.key}")
            elif kind == "gvtaken":
                self._replay_pending.discard((d["ns"], d["g"]))
            elif kind == "gdrop":
                key = (d["ns"], d["g"])
                self._replay_pending.discard(key)
                res = self._reservations.get(key)
                if res is not None:
                    self._reservations.pop(key, None)
                    self._epoch += 1
                    self._note_delta_locked(slices=res.slice_coords,
                                            why=f"replay gdrop {key}")
            elif kind == "evict":
                # the eviction INTENT re-queues (recovery prunes pods
                # that no longer exist); the ledger release replayed
                # from its own record
                self._evictions.append(d["p"])
                if d.get("sid") is not None and d.get("c"):
                    self._terminating_coords[d["p"]] = (
                        d["sid"],
                        frozenset(TopologyCoord(*c) for c in d["c"]),
                    )
                    self._epoch += 1
                    self._note_delta_locked(slices=(d["sid"],),
                                            why=f"replay evict {d['p']}")
            elif kind == "gterm":
                res = self._reservations.get((d["ns"], d["g"]))
                sids: set[str] = set()
                for pk, entry in d["pods"].items():
                    if res is not None:
                        res.terminating_victims.add(pk)
                    if entry[1]:
                        self._terminating_coords[pk] = (
                            entry[0],
                            frozenset(TopologyCoord(*c) for c in entry[1]),
                        )
                        sids.add(entry[0])
                self._epoch += 1
                self._note_delta_locked(slices=sids, why="replay gterm")
            elif kind == "gvgone":
                pk = d["p"]
                entry = self._terminating_coords.get(pk)
                if entry is not None:
                    self._terminating_coords.pop(pk, None)
                    self._epoch += 1
                    self._note_delta_locked(slices=(entry[0],),
                                            why=f"replay gvgone {pk}")
                for res in self._reservations.values():
                    res.terminating_victims.discard(pk)
            elif kind == "gbound":
                res = self._reservations.get((d["ns"], d["g"]))
                if res is not None:
                    res.record_assignment(
                        d["p"], d["sid"],
                        [TopologyCoord(*c) for c in d["c"]],
                    )
                    if (not res.committed
                            and len(res.assigned) >= res.group.min_member):
                        res.committed = True
                    self._epoch += 1
                    self._note_delta_locked(slices=(d["sid"],),
                                            why=f"replay gbound {d['p']}")
            elif kind == "guncommit":
                res = self._reservations.get((d["ns"], d["g"]))
                if res is not None:
                    res.committed = False
            elif kind == "gmrel":
                pk = d["p"]
                for res in self._reservations.values():
                    if pk in res.assigned:
                        sid = res.assigned[pk][0]
                        res.drop_assignment(pk)
                        if res.committed and not res.assigned:
                            self._reservations.pop(res.key, None)
                        self._epoch += 1
                        self._note_delta_locked(
                            slices=(sid,), why=f"replay gmrel {pk}")
                        break
            elif kind == "greas":
                pk = d["p"]
                coords = [TopologyCoord(*c) for c in d["c"]]
                for res in self._reservations.values():
                    entry = res.assigned.get(pk)
                    if entry is not None:
                        sid, old = entry
                        res.drop_assignment(pk)
                        pool = res.slice_coords.get(sid, set())
                        pool.difference_update(old)
                        pool.update(coords)
                        res.slice_coords[sid] = pool
                        res.record_assignment(pk, sid, list(coords))
                        self._epoch += 1
                        self._note_delta_locked(
                            slices=(sid,), why=f"replay greas {pk}")
                        break
            else:
                raise GangError(f"unknown gang journal record {kind!r}")

    def drop_reservation(self, key: tuple[str, str]) -> bool:
        """Forget a reservation WITHOUT evicting its members — the
        recovery reconcile's gang normalizer: the ledger is already
        correct, and the group re-restores from it via ``restore()``
        (a replayed reservation whose member binds were lost with the
        WAL tail must not shadow the rebuilt truth)."""
        with self._lock:
            # look up before popping: the no-such-gang path mutates
            # nothing and owes no epoch bump (epoch-discipline lint)
            res = self._reservations.get(key)
            if res is None:
                return False
            self._reservations.pop(key, None)
            self._epoch += 1
            self._note_delta_locked(slices=res.slice_coords,
                                    why=f"drop {key}")
            self._note_journal_locked("gdrop", {"ns": key[0],
                                                "g": key[1]})
            return True

    def finish_replay(self) -> list[tuple[str, str]]:
        """End-of-replay hygiene: drop reservations replayed with a
        deferred-eviction plan that never executed (no ``gvtaken``
        before the crash) and no bound members — their reserved box may
        overlap victims' still-occupied chips and no bind can ever
        execute the lost plan. The gang's next filter re-plans from
        scratch, exactly the legacy cold-rebuild behavior. Returns the
        dropped keys."""
        dropped: list[tuple[str, str]] = []
        with self._lock:
            for key in sorted(self._replay_pending):
                res = self._reservations.get(key)
                if res is not None and not res.assigned:
                    self._reservations.pop(key, None)
                    self._epoch += 1
                    self._note_delta_locked(
                        slices=res.slice_coords,
                        why=f"replay drop-pending {key}")
                    dropped.append(key)
            self._replay_pending.clear()
        return dropped


"""Scheduler layer (L5): slicefit allocator, extender, gang, policy."""

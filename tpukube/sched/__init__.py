"""Scheduler layer (L5): slicefit allocator, epoch-cached scheduling
snapshots, extender, gang, policy."""

"""Durable control-plane state: write-ahead journal + checkpointed
O(Δ) crash recovery (ISSUE 11 tentpole).

KubeGPU's single-extender control plane keeps all scheduling truth in
process memory; a crash costs an O(fleet) rebuild from pod annotations
— at 10k nodes cold start is dominated by ten thousand ``upsert_node``
decodes while the plane serves nothing (PAPER.md §1, ROADMAP "make
cold start O(Δ) too"). PR 10 already forces every mutation seam to
emit a typed delta; this module persists that exact stream:

  * :class:`StateJournal` — an append-only JSONL WAL. Every ledger /
    gang mutation seam (``ClusterState._note_journal_locked`` /
    ``GangManager._note_journal_locked``) enqueues one typed record;
    a dedicated drain thread (the ``trace.JsonlSink`` pattern) owns
    the file, so the decision lock never blocks on disk. Each record
    carries a CRC32 over its canonical JSON — a torn or corrupted
    tail is DETECTED, truncated, and absorbed by the reconcile pass,
    never silently replayed. The file rotates once to ``<path>.1`` at
    ``max_bytes``; rotation requests a prompt checkpoint so the live
    chain stays coverable.
  * ``Checkpoint`` — a periodic full snapshot (decoded node views +
    allocations + gang reservations + terminating masks + the WAL
    position they cover), captured in memory under the decision lock
    (O(allocs + changed nodes): node entries are memoized per payload)
    and written temp-file-then-``os.replace`` on the drain thread, so
    a crash mid-checkpoint leaves the previous checkpoint intact.
  * :func:`recover_extender` — the warm cold-start: load the latest
    valid checkpoint, replay the WAL tail through the REAL mutators,
    then reconcile against the apiserver only for the divergence set
    (per-node payload string compares and per-pod annotation compares;
    decode + commit only what actually moved). Restart-to-serving is
    O(Δ-since-checkpoint) instead of O(fleet) ``rebuild_from_pods``,
    and the PR 6 audit sentinel runs once at the end, asserting the
    recovered snapshot matches a from-scratch ledger rebuild.

Failure ladder (degrade, never be wrong): a torn/corrupt WAL tail →
truncate + reconcile; an invalid checkpoint → replay the whole WAL
from empty; a WAL gap (rotation outran checkpoints) or a structurally
undecodable checkpoint → :class:`JournalError`, and the caller falls
back to the legacy full rebuild on a FRESH extender.

``fsync`` policy: ``"off"`` (default) flushes each drain batch to the
OS but never fsyncs — a machine crash can lose the last few records,
which the reconcile pass absorbs exactly like a torn tail; ``"always"``
fsyncs every batch — bounded loss of zero at the cost of one fsync per
drained batch on the journal thread (never on the decision path).
Checkpoints fsync before rename under either policy.

All knobs (``journal_enabled``, ``journal_path``,
``checkpoint_interval_seconds``, ``journal_fsync``) default OFF with
byte-identical legacy behavior — nothing here is constructed, no
series render, no file is touched.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Optional

from tpukube.core import codec
from tpukube.sched.gang import GangError
from tpukube.sched.state import StateError

log = logging.getLogger("tpukube.journal")

#: checkpoint document schema version (bump on incompatible layout).
#: v2: head line (everything eager) + per-node JSONL lines addressed
#: by the head's node_index — the lazy-restore layout.
CHECKPOINT_VERSION = 2


class JournalError(RuntimeError):
    """The journal cannot produce a trustworthy state (WAL gap,
    undecodable checkpoint): the caller must fall back to the legacy
    full rebuild on a fresh extender — degraded, never wrong."""


def _canon(obj: Any) -> str:
    """Canonical JSON for CRC computation: writer and loader must
    serialize identically (sort_keys + compact separators; Python's
    float repr round-trips exactly through json)."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _with_crc(body: str, crc: int) -> str:
    """Append a ``"c"`` field to an already-serialized JSON object."""
    return body[:-1] + ',"c":%d}' % crc


def _ckpt_wal_seq_hint(ckpt_path: str) -> int:
    """The checkpoint's ``wal_seq`` read off the HEAD LINE (the first
    line of the v2 layout; the field sorts last in it, before the
    appended CRC), without parsing the document — a seq lower bound
    for numbering continuity when a landed checkpoint truncated the
    WAL it covered. Node lines never carry the key, so the head line's
    last match is the value."""
    import re

    try:
        with open(ckpt_path, "rb") as f:
            head = f.readline().decode("utf-8", "replace")
    except OSError:
        return 0
    hits = re.findall(r'"wal_seq":(\d+)', head)
    return int(hits[-1]) if hits else 0


def _last_seq_on_disk(path: str) -> int:
    """The last record seq the WAL tail holds (0 for missing/empty).
    Reads a bounded tail chunk and takes the last line that parses —
    a torn final line falls back to the one before it, which is a safe
    LOWER bound never exceeded by valid records."""
    best = _ckpt_wal_seq_hint(path + ".ckpt")
    for p in (f"{path}.1", path):
        try:
            with open(p, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 256 * 1024))
                tail = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        for line in reversed(tail.splitlines()):
            try:
                obj = json.loads(line)
                best = max(best, int(obj["s"]))
                break
            except (json.JSONDecodeError, KeyError, TypeError,
                    ValueError):
                continue
    return best


class StateJournal:
    """Append-only WAL + checkpoint writer; see the module docstring.

    Thread contract: ``note()`` is called from inside the ledger/gang
    locks and ONLY enqueues (deque append + condition notify). The
    drain thread owns serialization, the file, rotation, and checkpoint
    writes. ``data`` passed to note() must be freshly built and never
    mutated afterwards.
    """

    CKPT_WINDOW = 64      # checkpoint-latency samples for the summary
    RECOVERY_WINDOW = 16  # recovery-latency samples

    def __init__(self, path: str, max_bytes: int = 64 * 1024**2,
                 fsync: str = "off",
                 checkpoint_interval: float = 60.0,
                 events=None, clock=None) -> None:
        from tpukube.core.clock import SYSTEM

        self.path = path
        self.ckpt_path = path + ".ckpt"
        self.max_bytes = max_bytes
        self.fsync = fsync
        self.checkpoint_interval = checkpoint_interval
        self._events = events
        # scheduling-semantic time for the checkpoint cadence (FakeClock
        # compressible in the sim); latency MEASUREMENT stays real-time
        self._clock = clock if clock is not None else SYSTEM
        self._cond = threading.Condition()
        #: ("rec", seq, kind, data) | ("ckpt", doc) in enqueue order
        self._queue: deque = deque()
        self._closed = False
        # seq numbering CONTINUES across incarnations appending to the
        # same WAL (read off the file tail): a restart that skips or
        # fails recovery must never reuse seqs the file already holds —
        # the checkpoint's wal_seq cut depends on monotonicity
        self._seq = _last_seq_on_disk(path)
        # counters (tpukube_journal_* series; reads are lock-cheap)
        self.appends = 0
        self.bytes_total = 0
        self.rotations = 0
        self.checkpoints = 0
        self.replayed_total = 0
        self._ckpt_seconds: deque[float] = deque(maxlen=self.CKPT_WINDOW)
        self._recovery_seconds: deque[float] = deque(
            maxlen=self.RECOVERY_WINDOW)
        self.last_recovery: Optional[dict[str, Any]] = None
        self._ckpt_wanted = False
        self._last_ckpt_req = self._clock.monotonic()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._file = open(path, "a", encoding="utf-8")
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tpukube-journal",
        )
        self._thread.start()

    # -- the hot-path API (called under ledger/gang/decision locks) --------
    def note(self, kind: str, data: dict) -> None:
        """Enqueue one WAL record (non-blocking; dropped after close)."""
        with self._cond:
            if self._closed:
                return
            self._seq += 1
            self._queue.append(("rec", self._seq, kind, data))
            self.appends += 1
            self._cond.notify()

    def sync(self) -> None:
        """Durability barrier: block until every record enqueued before
        this call is flushed (and fsync'd under the ``always`` policy).
        The drain/decommission choreography syncs its cordon and
        un-ingest seams through this before acting on them — a crash
        right after a drain began must not forget WHICH capacity was
        leaving, and a decommission is only safe to report once the
        un-ingest record cannot be lost. Rare-path only: one barrier
        per drain transition, never per scheduling decision."""
        done = threading.Event()
        with self._cond:
            if self._closed:
                return
            self._queue.append(("sync", done))
            self._cond.notify()
        if not done.wait(timeout=30.0):
            log.error("journal sync barrier did not land within 30s "
                      "(%s)", self.path)

    def seq(self) -> int:
        """Last assigned record seq (the checkpoint's WAL position)."""
        with self._cond:
            return self._seq

    def set_seq(self, seq: int) -> None:
        """Continue numbering after a recovery replayed up to ``seq``."""
        with self._cond:
            self._seq = max(self._seq, int(seq))

    def force_seq(self, seq: int) -> None:
        """Pin numbering to exactly ``seq`` — ONLY safe right after
        ``compact_wal`` rewrote the file to end at ``seq``: a voided
        (corrupt/torn, cut-at-load) record may have carried a higher
        seq that the constructor's tail scan picked up, and leaving it
        would open a permanent gap in front of every future append."""
        with self._cond:
            self._seq = int(seq)

    def checkpoint_due(self, now: float) -> bool:
        with self._cond:
            return (self._ckpt_wanted
                    or now - self._last_ckpt_req
                    >= self.checkpoint_interval)

    def request_checkpoint(self, doc: dict) -> None:
        """Enqueue one checkpoint write (the drain thread serializes
        and lands it AFTER every record already queued, so the doc's
        ``wal_seq`` always covers what precedes it on disk)."""
        with self._cond:
            if self._closed:
                return
            self._ckpt_wanted = False
            self._last_ckpt_req = self._clock.monotonic()
            self._queue.append(("ckpt", doc, None))
            self._cond.notify()

    # -- drain thread ------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                items = list(self._queue)
                self._queue.clear()
                closing = self._closed
            try:
                self._write_out(items)
            except Exception:
                # the daemon keeps scheduling even when its journal
                # disk dies; recovery then degrades to the reconcile
                log.exception("journal drain failed (%s)", self.path)
            if closing:
                return

    def _write_out(self, items: list) -> None:
        f = self._file
        wrote = False
        for item in items:
            if item[0] == "sync":
                # barrier: everything written so far must be durable
                # before the waiter proceeds
                if wrote:
                    f.flush()
                    if self.fsync == "always":
                        os.fsync(f.fileno())
                    wrote = False
                item[1].set()
                continue
            if item[0] == "ckpt":
                if wrote:
                    # records queued before the checkpoint must be ON
                    # DISK before the doc naming their seq lands
                    f.flush()
                try:
                    self._write_checkpoint(item[1])
                finally:
                    if item[2] is not None:
                        item[2].set()  # write_checkpoint_sync waiter
                continue
            _, seq, kind, data = item
            body = _canon({"s": seq, "k": kind, "d": data})
            crc = zlib.crc32(body.encode("utf-8"))
            line = _with_crc(body, crc) + "\n"
            nbytes = len(line.encode("utf-8"))
            if (self.max_bytes > 0 and self._bytes > 0
                    and self._bytes + nbytes > self.max_bytes):
                f.flush()
                f.close()
                try:
                    os.replace(self.path, f"{self.path}.1")
                except OSError:
                    pass  # worst case we truncate in place below
                # append mode, like the constructor's handle: every
                # write lands at EOF regardless of stream position, so
                # a later truncate-to-zero (checkpoint landing) cannot
                # leave a NUL hole in front of the next record
                f = self._file = open(self.path, "a", encoding="utf-8")
                with self._cond:
                    self._bytes = 0
                    self.rotations += 1
                    # the live file no longer reaches back to the last
                    # checkpoint's position: ask for a prompt one
                    self._ckpt_wanted = True
            f.write(line)
            wrote = True
            with self._cond:
                self._bytes += nbytes
                self.bytes_total += nbytes
        if wrote:
            f.flush()
            if self.fsync == "always":
                os.fsync(f.fileno())

    def _write_checkpoint(self, doc: dict) -> None:
        """Land one checkpoint capture: HEAD LINE (CRC'd canonical
        JSON carrying everything eager plus the node_index) followed by
        one JSONL line per node, addressed by head-relative offsets.
        ``("ref", ...)`` entries copy their bytes verbatim from the
        previous checkpoint file (the capture's dup'd fd). A failure
        keeps the previous checkpoint intact — temp file + atomic
        rename, fsync'd."""
        t0 = time.perf_counter()
        head = doc["head"]
        old_fd = doc.get("old_fd")
        try:
            lines: list[bytes] = []
            index: dict[str, list] = {}
            rel = 0
            for e in doc["node_entries"]:
                if e[0] == "line":
                    _, name, line, crc, sid, pcrc, plen = e
                    raw = line.encode("utf-8")
                else:
                    _, name, off, length, crc, sid, pcrc, plen = e
                    if old_fd is None:
                        raise OSError(f"lazy ref for {name} without an "
                                      f"open previous checkpoint")
                    raw = os.pread(old_fd, length, off)
                    if zlib.crc32(raw) != crc:
                        raise OSError(f"stale lazy ref for {name}")
                index[name] = [rel, len(raw), crc, sid, pcrc, plen]
                lines.append(raw + b"\n")
                rel += len(raw) + 1
            head = dict(head)
            head["node_index"] = index
            # total node-line bytes: the loader refuses a checkpoint
            # whose body was torn off even when the head line itself
            # survived intact (head-CRC alone cannot see past itself)
            head["data_bytes"] = rel
            body = _canon(head)
            head_line = (
                _with_crc(body, zlib.crc32(body.encode("utf-8"))) + "\n"
            ).encode("utf-8")
            tmp = self.ckpt_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(head_line)
                f.writelines(lines)
                f.flush()
                # checkpoints always fsync before the atomic rename — a
                # torn checkpoint would silently cost the WHOLE warm
                # path, and one fsync per interval is noise (the
                # per-record policy is where fsync cost actually lives)
                os.fsync(f.fileno())
            os.replace(tmp, self.ckpt_path)
        except OSError:
            # keep the previous checkpoint; the next cadence retries
            log.exception("checkpoint write failed (%s)", self.ckpt_path)
            return
        finally:
            if old_fd is not None:
                try:
                    os.close(old_fd)
                except OSError:
                    pass
        # log truncation: every record on disk right now has seq <= the
        # doc's wal_seq (records are enqueued under the decision lock
        # that captured the doc, and this thread writes in queue
        # order), so the checkpoint covers the whole file — drop it.
        # Recovery's load_wal then reads a short tail instead of the
        # whole history, which is what keeps restart O(Δ).
        f = self._file
        if f is not None:
            f.flush()
            f.truncate(0)
            # reset the stream position too: the handle is append-mode
            # (writes go to EOF either way), but a stale position must
            # never be trusted by anything downstream
            f.seek(0)
        try:
            os.unlink(f"{self.path}.1")
        except OSError:
            pass
        dt = time.perf_counter() - t0
        with self._cond:
            self._bytes = 0
            self.checkpoints += 1
            self._ckpt_seconds.append(dt)
        if self._events is not None:
            try:
                self._events.emit(
                    "CheckpointWritten", obj="journal/checkpoint",
                    message="control-plane checkpoint written (ledger + "
                            "gang reservations + WAL position)",
                )
            except Exception:
                log.exception("event emit failed: CheckpointWritten")

    def write_checkpoint_sync(self, doc: dict) -> None:
        """Checkpoint now and WAIT for it to land. The write still runs
        on the drain thread, IN QUEUE ORDER — the single-writer
        discipline the file depends on: a caller-thread write would
        race the drain's buffered appends around the post-checkpoint
        truncation and could tear a record mid-file (cold-start callers
        enqueue thousands of records right before this)."""
        done = threading.Event()
        with self._cond:
            if self._closed:
                return
            self._ckpt_wanted = False
            self._last_ckpt_req = self._clock.monotonic()
            self._queue.append(("ckpt", doc, done))
            self._cond.notify()
        if not done.wait(timeout=30.0):
            log.error("checkpoint did not land within 30s (%s)",
                      self.ckpt_path)

    def compact_wal(self, records: list[dict]) -> None:
        """Rewrite the live WAL to exactly ``records`` (the valid,
        CRC-verified set a recovery loaded) and drop the rotation: a
        torn/corrupt tail is cut for good (the loader stops at the
        first bad line, so leaving it would shadow future appends),
        and rotated history collapses into one live file. O(tail) —
        the records a recovery replays — and no checkpoint write or
        fsync on the restart-to-serving path. Runs before serving; the
        drain thread is idle."""
        with self._cond:
            if self._file is not None:
                self._file.truncate(0)
                self._file.seek(0)
                total = 0
                for rec in records:
                    body = _canon({"s": rec["s"], "k": rec["k"],
                                   "d": rec["d"]})
                    line = _with_crc(body, rec["c"]) + "\n"
                    self._file.write(line)
                    total += len(line.encode("utf-8"))
                self._file.flush()
                self._bytes = total
        try:
            os.unlink(f"{self.path}.1")
        except OSError:
            pass

    # -- recovery bookkeeping ----------------------------------------------
    def note_recovery(self, stats: dict[str, Any]) -> None:
        with self._cond:
            self.last_recovery = dict(stats)
            self._recovery_seconds.append(stats["recovery_s"])
            self.replayed_total += stats.get("replayed", 0)

    def checkpoint_seconds_snapshot(self) -> list[float]:
        with self._cond:
            return list(self._ckpt_seconds)

    def recovery_seconds_snapshot(self) -> list[float]:
        with self._cond:
            return list(self._recovery_seconds)

    # -- lifecycle ---------------------------------------------------------
    def crash(self) -> None:
        """Simulated process death (sim crash_extender): queued-but-
        undrained records are LOST — exactly what a real crash loses —
        and the file handle closes without flushing the queue."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            for item in self._queue:
                if item[0] == "ckpt" and item[2] is not None:
                    item[2].set()  # never strand a sync waiter
                elif item[0] == "sync":
                    item[1].set()  # barrier waiters neither
            self._queue.clear()
            self._cond.notify()
        self._thread.join(timeout=10.0)
        if self._file is not None:
            self._file.close()
            self._file = None

    def close(self) -> None:
        """Drain what is queued, stop the thread, close the file.
        Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=10.0)
        if self._file is not None:
            self._file.close()
            self._file = None

    def stats(self) -> dict[str, Any]:
        """The /statusz "journal" section."""
        with self._cond:
            last_ckpt = (self._ckpt_seconds[-1]
                         if self._ckpt_seconds else None)
            return {
                "enabled": True,
                "path": self.path,
                "seq": self._seq,
                "appends": self.appends,
                "bytes_total": self.bytes_total,
                "bytes_live": self._bytes,
                "rotations": self.rotations,
                "checkpoints": self.checkpoints,
                "last_checkpoint_s": (round(last_ckpt, 6)
                                      if last_ckpt is not None else None),
                "checkpoint_interval_seconds": self.checkpoint_interval,
                "fsync": self.fsync,
                "replayed_total": self.replayed_total,
                "last_recovery": self.last_recovery,
            }


# -- loading -----------------------------------------------------------------

def load_checkpoint(path: str
                    ) -> Optional[tuple[dict, int, int]]:
    """The checkpoint HEAD plus an open read fd and the node-data
    start offset — (head, fd, data_start) — or None when
    missing/torn/corrupt (recovery then replays the whole WAL from
    empty — the next rung of the failure ladder, not an error). Only
    the head line is read and CRC-verified here; node lines load
    lazily through the fd (each carries its own CRC in the head's
    node_index). CRC verification runs over the RAW head bytes (the
    writer appended ``"c"`` to an already-serialized body), so a
    multi-MB checkpoint is never re-serialized just to check it. The
    CALLER owns the returned fd."""
    try:
        f = open(path, "rb")
    except OSError:
        return None
    with f:
        first = f.readline()
    data_start = len(first)
    text = first.decode("utf-8", "replace").rstrip("\n")
    # written as  <canonical body minus "}"> + ',"c":<crc>}'  — split
    # the trailer off and CRC the body verbatim
    body, sep, trailer = text.rpartition(',"c":')
    if not sep or not trailer.endswith("}") \
            or not trailer[:-1].isdigit():
        log.error("checkpoint %s is torn/corrupt (no CRC trailer); "
                  "ignoring it", path)
        return None
    crc = int(trailer[:-1])
    body += "}"
    if crc != zlib.crc32(body.encode("utf-8")):
        log.error("checkpoint %s fails its CRC; ignoring it", path)
        return None
    try:
        obj = json.loads(body)
    except json.JSONDecodeError as e:
        log.error("checkpoint %s is undecodable past its CRC (%s); "
                  "ignoring it", path, e)
        return None
    if obj.get("v") != CHECKPOINT_VERSION:
        log.error("checkpoint %s has version %r (want %d); ignoring it",
                  path, obj.get("v"), CHECKPOINT_VERSION)
        return None
    try:
        size = os.path.getsize(path)
    except OSError:
        return None
    if size != data_start + obj.get("data_bytes", -1):
        # body torn off behind an intact head: the node lines the
        # index points at are gone — the whole checkpoint is void
        log.error("checkpoint %s: body is %d byte(s), head promises "
                  "%s; ignoring it", path, size - data_start,
                  obj.get("data_bytes"))
        return None
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError as e:
        log.error("checkpoint %s: cannot reopen for lazy reads: %s",
                  path, e)
        return None
    return obj, fd, data_start


def load_wal(path: str) -> tuple[list[dict], dict[str, int]]:
    """WAL records from ``<path>.1`` (the rotation, if any) then
    ``path``, in order, CRC-verified. Reading STOPS at the first torn
    or CRC-failing line of each file — everything after an undecodable
    record is untrusted, and the reconcile pass covers whatever was
    cut. Returns (records, {"torn": n, "bad_crc": n})."""
    records: list[dict] = []
    info = {"torn": 0, "bad_crc": 0}
    for p in (f"{path}.1", path):
        try:
            f = open(p, encoding="utf-8")
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                    body = _canon({"s": obj["s"], "k": obj["k"],
                                   "d": obj["d"]})
                except (json.JSONDecodeError, KeyError, TypeError):
                    info["torn"] += 1
                    log.warning("%s: torn WAL line after seq %s; "
                                "truncating here", p,
                                records[-1]["s"] if records else 0)
                    break
                if obj.get("c") != zlib.crc32(body.encode("utf-8")):
                    info["bad_crc"] += 1
                    log.warning("%s: WAL record seq %s fails its CRC; "
                                "truncating here", p, obj.get("s"))
                    break
                records.append(obj)
    return records, info


# -- replay ------------------------------------------------------------------

def replay_records(extender, records: list[dict]) -> int:
    """Apply a WAL tail through the real mutators (journal detached by
    the caller, so nothing re-records). A record that fails to apply is
    logged and SKIPPED — the apiserver reconcile owns whatever truth it
    carried; replay must never abort recovery over one record."""
    state, gang = extender.state, extender.gang
    applied = 0
    for rec in records:
        kind, d = rec["k"], rec["d"]
        try:
            if kind == "commit":
                state.commit(codec.decode_alloc(d["a"]))
            elif kind == "release":
                state.release(d["p"])
            elif kind == "node":
                state.upsert_node(d["n"], dict(d["anno"]))
            elif kind == "cordon":
                # drain choreography (ISSUE 19): cordon/uncordon is a
                # plain ledger mutation — idempotent, unknown names
                # skipped by the mutator itself
                state.set_cordon(list(d["n"]), bool(d["c"]))
            elif kind == "unnodes":
                # un-ingest batch: nodes with live allocations are
                # skipped loudly inside remove_nodes (WAL order places
                # releases first, so replay normally finds them free)
                state.remove_nodes(list(d["n"]))
            elif kind == "nodes":
                # one bulk-ingest batch (ISSUE 15): replay through the
                # same fast path; per-item errors are logged by the
                # ingest and the reconcile covers them
                for out in state.ingest_nodes([
                    {"name": n, "annotations": dict(a)}
                    for n, a in d["items"]
                ]):
                    if isinstance(out, dict) and out.get("error"):
                        log.error("journal replay: bulk-ingest item "
                                  "failed: %s — the apiserver "
                                  "reconcile covers it", out["error"])
            else:
                gang.apply_journal(rec)
            applied += 1
        except (StateError, GangError, codec.CodecError, KeyError,
                TypeError, ValueError) as e:
            log.error("journal replay: seq %s (%s) failed: %s — the "
                      "apiserver reconcile covers it", rec.get("s"),
                      kind, e)
    return applied


# -- recovery ----------------------------------------------------------------

def _api_call(fn: Callable, what: str, attempts: int = 64):
    """An apiserver read that rides out transient faults (recovery may
    run inside the same storm that killed the process). No backoff
    sleeps: recovery happens before serving, and the chaos tests need
    determinism, not politeness."""
    from tpukube.apiserver import transient_api_error

    last: Optional[Exception] = None
    for _ in range(attempts):
        try:
            return fn()
        except Exception as e:
            if not transient_api_error(e):
                raise
            last = e
    raise JournalError(
        f"apiserver unreachable during recovery ({what}): {last}"
    )


def recover_extender(extender, api) -> dict[str, Any]:
    """The journal-backed cold start: checkpoint + WAL tail + O(Δ)
    apiserver reconcile; see the module docstring. Returns a stats
    dict; raises :class:`JournalError` when the journal cannot produce
    a trustworthy base (the caller then rebuilds a FRESH extender the
    legacy way — a failed recovery may have half-restored state)."""
    from tpukube.core.types import TopologyCoord, canonical_link
    from tpukube.sched.snapshot import ClusterSnapshot, SliceSnapshot

    journal = extender.journal
    if journal is None:
        raise JournalError("recover_extender needs journal_enabled")
    events = extender.events
    t0 = time.perf_counter()
    state, gang = extender.state, extender.gang
    # detach: replayed mutations must not re-record into the WAL
    state.set_journal(None)
    gang.set_journal(None)
    ckpt_fd: Optional[int] = None
    fd_owned = False
    try:
        loaded = load_checkpoint(journal.ckpt_path)
        ckpt: Optional[dict] = None
        data_start = 0
        if loaded is not None:
            ckpt, ckpt_fd, data_start = loaded
            fd_owned = True
        records, wal_info = load_wal(journal.path)
        wal_seq = int(ckpt["wal_seq"]) if ckpt is not None else 0
        tail = [r for r in records if int(r["s"]) > wal_seq]
        expect = wal_seq
        for r in tail:
            expect += 1
            if int(r["s"]) != expect:
                raise JournalError(
                    f"WAL gap: expected seq {expect}, found {r['s']} "
                    f"(rotation outran checkpoints?)"
                )
        restored_allocs = 0
        restored_gangs = 0
        if ckpt is not None:
            node_index = {
                name: [data_start + e[0], e[1], e[2], e[3], e[4], e[5]]
                for name, e in ckpt.get("node_index", {}).items()
            }
            restored_allocs = state.restore_checkpoint(
                ckpt["state"], ckpt_fd, node_index
            )
            fd_owned = False  # ownership moved into the ledger
            restored_gangs = gang.restore_checkpoint(ckpt["gang"])
            snap_doc = ckpt.get("snap")
            if snap_doc is not None and set(snap_doc) == set(
                state.slice_ids()
            ):
                # seed the scheduling snapshot: the first lookups HIT
                # instead of forcing the O(chips) rebuild that would
                # eagerly materialize every lazy node; the audit
                # sentinel (below, and sampled at runtime) holds the
                # seed to ledger truth
                slices = {}
                for sid, sd in snap_doc.items():
                    slices[sid] = SliceSnapshot(
                        slice_id=sid,
                        mesh=state.slice_mesh(sid),
                        occupied=frozenset(
                            TopologyCoord(*c) for c in sd["occ"]),
                        reserved=frozenset(
                            TopologyCoord(*c) for c in sd["res"]),
                        unhealthy=frozenset(
                            TopologyCoord(*c) for c in sd["unh"]),
                        terminating=frozenset(
                            TopologyCoord(*c) for c in sd["term"]),
                        broken=frozenset(
                            canonical_link(a, b) for a, b in sd["brk"]),
                        used_shares=int(sd["used"]),
                        total_shares=int(sd["total"]),
                        # "crd" is written only when non-empty (drain
                        # off ⇒ checkpoint bytes unchanged)
                        cordoned=frozenset(
                            TopologyCoord(*c)
                            for c in sd.get("crd", ())),
                    )
                extender.snapshots.seed(ClusterSnapshot(
                    key=extender.snapshots.epoch_key(), slices=slices,
                ))
        replayed = replay_records(extender, tail)
        dropped_pending = gang.finish_replay()
        # reattach BEFORE the reconcile: its mutations are NEW history
        # and must hit the WAL like any other — the compact first cuts
        # any torn/corrupt tail so future appends stay loadable (and
        # prunes checkpoint-covered records: the tail is all a future
        # recovery replays), and the seq pin closes the hole a voided
        # tail record's higher seq would otherwise leave in front of
        # every future append
        journal.compact_wal(tail)
        # never below the checkpoint's position: a WAL compacted by an
        # earlier recovery leaves the tail empty while wal_seq stands
        journal.force_seq(max(
            tail[-1]["s"] if tail else 0, wal_seq,
        ))
        state.set_journal(journal)
        gang.set_journal(journal)
        if wal_info["torn"] or wal_info["bad_crc"]:
            try:
                events.emit(
                    "JournalTruncated", obj="journal/wal", type="Warning",
                    message=f"WAL tail cut at load ({wal_info['torn']} "
                            f"torn, {wal_info['bad_crc']} bad-CRC "
                            f"line(s)); the apiserver reconcile covers "
                            f"the cut records",
                )
            except Exception:
                log.exception("event emit failed: JournalTruncated")

        # seed the capture memo with the restored allocations so the
        # post-recovery checkpoint re-encodes nothing that round-
        # tripped intact
        if ckpt is not None:
            alloc_cache = extender._ckpt_cache.setdefault("allocs", {})
            sigs = ckpt["state"].get("alloc_index", {})
            ledger_now = {a.pod_key: a for a in state.allocations()}
            for obj in ckpt["state"].get("allocs", ()):
                key = obj.get("pod")
                entry = ledger_now.get(key)
                sig = sigs.get(key)
                if entry is not None and sig is not None:
                    alloc_cache[key] = (entry, obj,
                                        (int(sig[0]), int(sig[1])))

        # ---- reconcile: apiserver truth wins, O(divergence) work ----
        # nodes: a payload SIGNATURE COMPARE per node (lazy nodes stay
        # lazy — crc32+length against the checkpoint index, one lock
        # round-trip for the fleet); only changed or unknown nodes pay
        # a decode, via the recorded upsert_node decision the legacy
        # rebuild also uses
        changed_nodes = 0
        node_objs: dict[str, dict] = {}
        node_payloads: dict[str, str] = {}
        for obj in _api_call(api.list_nodes, "list_nodes"):
            meta = obj.get("metadata") or {}
            name = meta.get("name")
            if not name:
                continue
            payload = (meta.get("annotations") or {}).get(
                codec.ANNO_NODE_TOPOLOGY)
            if payload is None:
                continue
            node_objs[name] = obj
            node_payloads[name] = payload
        matching = state.nodes_matching_payloads(node_payloads)
        for name, obj in node_objs.items():
            if name in matching:
                continue
            annotations = dict(
                (obj.get("metadata") or {}).get("annotations") or {})
            out = extender.handle(
                "upsert_node", {"name": name, "annotations": annotations},
            )
            if out.get("error"):
                log.error("recovery: node %s annotation rejected: %s",
                          name, out["error"])
            else:
                changed_nodes += 1
        # pods: the ledger vs the live, bound, non-terminal annotated
        # set — a pod whose alloc annotation still matches its
        # checkpoint signature AND its ledger entry is proven
        # consistent without any decode; only the divergence set runs
        # the legacy lifecycle filter (which decodes and logs loudly)
        from tpukube.apiserver import TERMINAL_PHASES, live_alloc_pods

        alloc_index = (ckpt["state"].get("alloc_index", {})
                       if ckpt is not None else {})
        raw_pods = _api_call(api.list_pods, "list_pods")
        present: set[str] = set()
        ledger = {a.pod_key: a for a in state.allocations()}
        checked: set[str] = set()
        candidates: list[dict] = []
        for p in raw_pods:
            meta = p.get("metadata") or {}
            name = meta.get("name")
            if not name:
                continue
            key = f"{meta.get('namespace', 'default')}/{name}"
            present.add(key)
            annos = meta.get("annotations") or {}
            payload = annos.get(codec.ANNO_ALLOC)
            if not payload:
                continue
            phase = (p.get("status") or {}).get("phase")
            bound = (p.get("spec") or {}).get("nodeName")
            entry = ledger.get(key)
            if entry is None and (phase in TERMINAL_PHASES or not bound):
                # annotation residue with no ledger entry: nothing to
                # reconcile and nothing to log — the legacy filter
                # would only narrate the skip
                continue
            sig = alloc_index.get(key)
            if (entry is not None and sig is not None
                    and phase not in TERMINAL_PHASES
                    and bound == entry.node_name):
                raw = payload.encode("utf-8")
                uid = str(meta.get("uid") or "")
                if (sig[0] == zlib.crc32(raw) and sig[1] == len(raw)
                        and (not entry.uid or not uid
                             or entry.uid == uid)):
                    checked.add(key)
                    continue
            candidates.append(p)
        live: dict[str, tuple[dict, Any]] = {}
        for annos, planned, key in live_alloc_pods(candidates):
            live[key] = (annos, planned)
        stale = sorted(k for k in ledger
                       if k not in live and k not in checked)
        # gangs touched by the divergence set must rebuild WHOLE from
        # the reconciled ledger: a replayed reservation whose member
        # binds were lost with the WAL tail would otherwise shadow the
        # rebuilt truth (collected BEFORE the releases detach members)
        affected_gangs: set[tuple[str, str]] = set()
        res_by_pod: dict[str, tuple[str, str]] = {}
        for res in gang.snapshot():
            for pk in res.assigned:
                res_by_pod[pk] = res.key
        for k in stale:
            if k in res_by_pod:
                affected_gangs.add(res_by_pod[k])
            # recorded release decisions: the journal-restored entry has
            # no live pod behind it (completed / evicted mid-crash)
            extender.handle("release", {"pod_key": k})
        divergent: list[tuple[str, dict]] = []
        for key in sorted(live):
            annos, planned = live[key]
            entry = ledger.get(key)
            if (planned is not None and entry is not None
                    and entry.node_name == planned.node_name
                    and sorted(entry.device_ids)
                    == sorted(planned.device_ids)):
                continue
            if entry is not None:
                extender.handle("release", {"pod_key": key})
            gname = annos.get(codec.ANNO_POD_GROUP)
            if gname:
                affected_gangs.add((key.split("/", 1)[0], gname))
            if key in res_by_pod:
                affected_gangs.add(res_by_pod[key])
            divergent.append((key, annos))
        # ledger first (gang restoration runs below against the FULL
        # reconciled membership — never a divergent-only subset)
        readded = len(state.rebuild_from_pods(
            [annos for _, annos in divergent]
        ))
        # dangling-member scan: every live gang pod with a ledger entry
        # must be ASSIGNED in its group's reservation — a gbound (or the
        # whole gre) lost with the WAL tail otherwise leaves committed
        # members invisible to their gang, the partial-gang-death shape
        # the restore machinery exists to prevent
        assigned_now: dict[tuple[str, str], set] = {}
        for res in gang.snapshot():
            assigned_now[res.key] = set(res.assigned)
        for p in raw_pods:
            meta = p.get("metadata") or {}
            name = meta.get("name")
            annos = meta.get("annotations") or {}
            gname = annos.get(codec.ANNO_POD_GROUP)
            if not name or not gname:
                continue
            ns = meta.get("namespace", "default")
            key = f"{ns}/{name}"
            if state.allocation(key) is None:
                continue
            if key not in assigned_now.get((ns, gname), ()):
                affected_gangs.add((ns, gname))
        for gkey in sorted(affected_gangs):
            gang.drop_reservation(gkey)
        if affected_gangs:
            _restore_affected_gangs(extender, raw_pods, affected_gangs)
        # replayed eviction intents and terminating masks for pods that
        # no longer exist resolve now (their confirm channel died with
        # the old process; a pod that still exists keeps its intent and
        # the fresh executor completes the pre-crash all-or-nothing)
        keep = [p for p in extender.pending_evictions if p in present]
        extender.pending_evictions.clear()
        extender.pending_evictions.extend(keep)
        for pk in gang.terminating_pod_keys():
            if pk not in present:
                extender.handle("victim_gone", {"pod_key": pk})
        divergences = len(stale) + len(divergent)

        # ---- the PR 6 sentinel, once, riding the audit knob: with
        # snapshot_audit_rate > 0 the recovered snapshot must equal a
        # from-scratch ledger rebuild before serving begins (scenario
        # 13's acceptance runs at rate 1.0; rate 0 keeps the two full
        # O(chips) builds off the restart-to-serving path) ----
        if extender.snapshots.audit_rate > 0.0:
            extender.snapshots.audit_now()
        # request a FRESH checkpoint now (async — the drain thread
        # writes it): a crashy environment must not wait a full
        # checkpoint interval before each incarnation becomes warmly
        # recoverable, or repeated crashes degrade every recovery to
        # whole-WAL replays
        journal.request_checkpoint(extender.checkpoint_doc())
        recovery_s = time.perf_counter() - t0
        # drain the remaining lazy views OFF the serving path: by the
        # time the first full-fleet scan arrives (a structural rebuild,
        # a metrics scrape), the warmer has usually materialized
        # everything already
        _start_warmer(state)
        stats = {
            "mode": "warm",
            "recovery_s": round(recovery_s, 6),
            "checkpoint": ckpt is not None,
            "restored_allocs": restored_allocs,
            "restored_gangs": restored_gangs,
            "replayed": replayed,
            "dropped_pending_reservations": len(dropped_pending),
            "wal_torn": wal_info["torn"],
            "wal_bad_crc": wal_info["bad_crc"],
            "nodes_changed": changed_nodes,
            "pods_diverged": len(divergent),
            "pods_stale": len(stale),
            "pods_readded": readded,
            "divergences": divergences,
        }
        journal.note_recovery(stats)
        try:
            if divergences:
                events.emit(
                    "RecoveryDiverged", obj="journal/recovery",
                    type="Warning",
                    message=f"recovered state diverged from the "
                            f"apiserver on {divergences} pod(s); "
                            f"reconciled",
                )
            events.emit(
                "RecoveryCompleted", obj="journal/recovery",
                message="journal recovery completed "
                        "(checkpoint + WAL replay + reconcile)",
            )
        except Exception:
            log.exception("event emit failed: RecoveryCompleted")
        log.warning(
            "journal recovery: %d alloc(s) + %d gang(s) from the "
            "checkpoint, %d WAL record(s) replayed, %d node(s) + %d "
            "pod(s) reconciled in %.3fs",
            restored_allocs, restored_gangs, replayed, changed_nodes,
            divergences, recovery_s,
        )
        return stats
    except JournalError:
        if fd_owned and ckpt_fd is not None:
            try:
                os.close(ckpt_fd)
            except OSError:
                pass
        raise
    except (KeyError, TypeError, ValueError, AttributeError,
            codec.CodecError, StateError, GangError) as e:
        # a structurally-broken checkpoint/WAL may have half-restored
        # state: the caller must rebuild on a FRESH extender
        if fd_owned and ckpt_fd is not None:
            try:
                os.close(ckpt_fd)
            except OSError:
                pass
        raise JournalError(f"recovery failed: {e}") from e


def _restore_affected_gangs(extender, raw_pods: list[dict],
                            affected: set) -> None:
    """Rebuild the affected groups' reservations from the RECONCILED
    ledger (their stale reservations were dropped): every live member
    with a committed allocation joins, exactly the legacy cold
    rebuild's gang semantics — committed gangs restore with their
    members' chips, mid-assembly gangs re-derive a completable box or
    roll back."""
    state, gang = extender.state, extender.gang
    members: dict[tuple, list] = {k: [] for k in affected}
    specs: dict[tuple, Any] = {}
    for p in raw_pods:
        meta = p.get("metadata") or {}
        name = meta.get("name")
        if not name:
            continue
        ns = meta.get("namespace", "default")
        annos = meta.get("annotations") or {}
        gname = annos.get(codec.ANNO_POD_GROUP)
        if gname is None or (ns, gname) not in members:
            continue
        alloc = state.allocation(f"{ns}/{name}")
        if alloc is None:
            continue
        try:
            group = codec.pod_group_from_annotations(dict(annos))
        except codec.CodecError as e:
            log.warning("gang reconcile: pod %s/%s carries an "
                        "undecodable pod-group annotation (%s)",
                        ns, name, e)
            continue
        if group is None:
            continue
        members[(ns, gname)].append(alloc)
        specs[(ns, gname)] = group
    for key, allocs in members.items():
        if allocs and key in specs:
            gang.restore(key[0], specs[key], allocs)


def _start_warmer(state) -> None:
    """Background materializer for lazily-restored node views: drains
    the fleet in small batches so the steady-state serving path never
    meets a cold node, without the restart paying O(fleet) up front."""
    def run() -> None:
        # brief head start for the restart epilogue and the first
        # webhooks: warming is strictly background work and must not
        # steal interpreter time from restart-to-serving itself
        time.sleep(0.05)
        while state.warm_pending(512):
            pass

    threading.Thread(target=run, daemon=True,
                     name="tpukube-journal-warmer").start()

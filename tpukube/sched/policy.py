"""Multi-tenant policy: bin-packing + priority preemption (SURVEY.md C11).

BASELINE config 5's scenario: a cluster running low-priority burst
inference pods must yield a CONTIGUOUS slice when a high-priority training
gang arrives. Evicting the right victims to open a contiguous box is
NP-flavored (SURVEY.md §9.3); this is the bounded exact-sweep heuristic:

  1. Victim granularity is a WORKLOAD: a non-gang pod, or an entire gang
     (members + reservation). Gangs are all-or-nothing in death as in
     birth — evicting individual members would strand the rest on a
     broken slice and hand their chips back to the gang's own reservation.
  2. Build a "blocked" grid: unhealthy chips plus every chip of workloads
     whose priority >= the preemptor's. These can never be taken.
  3. Sweep every candidate box of the needed volume/shape over that grid
     (the slicefit summed-area machinery, so the sweep is O(mesh)).
  4. Cost of a box = (sum of victim workload priorities, victim count,
     box surface, -contact): prefer cheap evictions, then few, then a
     compact snug box. Deterministic tie-break on origin.

The extender applies the winning plan in TWO PHASES: at /filter it only
records the victims on the gang's reservation; at the gang's first /bind
it executes them — non-gang victims released and queued for eviction,
gang victims dissolved wholesale. A planned-but-never-bound gang (crash,
higher-priority queue churn) therefore costs no victim its chips: the TTL
sweep drops the reservation and the victims were never touched.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from tpukube.core.mesh import MeshSpec
from tpukube.core.types import DEFAULT_SLICE, TopologyCoord
from tpukube.sched import slicefit
from tpukube.sched.snapshot import sweep_for

log = logging.getLogger("tpukube.policy")


@dataclass(frozen=True)
class Workload:
    """Unit of preemption: one pod, or one whole gang."""

    id: str                      # pod_key, or "gang:<ns>/<name>"
    priority: int                # blocking priority (max member priority)
    cost: int                    # eviction cost (sum of member priorities)
    coords: frozenset[TopologyCoord]  # every chip it holds (gangs include
                                      # their unassigned reserved chips);
                                      # coords are local to slice_id
    pod_keys: tuple[str, ...] = ()
    gang_key: Optional[tuple[str, str]] = None
    slice_id: str = DEFAULT_SLICE  # the ICI domain the chips live in
    tenant: str = ""  # serving-plane owner ("" when tenancy is off)


@dataclass(frozen=True)
class PreemptionPlan:
    coords: list[TopologyCoord]   # the box the gang will take
    victims: list[Workload]       # workloads to evict, deterministic order
    cost_priority_sum: int
    victim_count: int


def find_preemption_plan(
    workloads: list[Workload],
    mesh: MeshSpec,
    unhealthy: set[TopologyCoord],
    total: int,
    shape: Optional[tuple[int, int, int]],
    preemptor_priority: int,
    broken: Optional[set] = None,
    overshare: Optional[dict[str, float]] = None,
) -> Optional[PreemptionPlan]:
    """Cheapest victim set whose eviction opens a contiguous `total`-chip
    box (or the exact `shape`). None when no eligible box exists. Boxes
    spanning a downed ICI link are never candidates — evicting pods cannot
    repair a link, so such a box would be a degraded slice.

    ``overshare`` (the tenancy plane's tenant -> over-entitlement map)
    biases victim choice: at equal priority cost, the box whose victims
    belong to the MOST over-share tenants wins — the lowest-share
    preemptor takes chips back from whoever is furthest over. None (the
    default, and every tenancy-off call) contributes a constant 0.0 to
    the ranking, leaving the legacy order bit-identical."""
    # A chip may host several workloads (fractional vTPU co-tenants): all
    # of them must be evicted to free it, so the owner map is coord->list.
    owner: dict[TopologyCoord, list[Workload]] = {}
    blocked = set(unhealthy)
    for w in workloads:
        for c in w.coords:
            owner.setdefault(c, []).append(w)
        if w.priority >= preemptor_priority:
            blocked |= w.coords

    # Sweep candidate boxes over a grid where only BLOCKED chips count as
    # occupied — victims' chips look free because evicting them is the
    # plan. The grid is REQUEST-specific (depends on the preemptor's
    # priority), so it rides an ad-hoc sweep built through the snapshot
    # module's constructor seam; origin enumeration and contact scoring
    # still come batched per shape tier from the vectorized sweep.
    candidates = slicefit.iter_free_boxes_in(
        sweep_for(mesh, blocked),
        count=total if shape is None else None,
        shape=shape,
        broken=broken,
    )

    over = overshare or {}
    best: Optional[tuple] = None  # (key, cost, coords, victims)
    for sb in candidates:
        coords = slicefit.box_coords(mesh, sb.box)
        victims = {
            w.id: w for c in coords for w in owner.get(c, ())
        }
        cost = sum(w.cost for w in victims.values())
        # tenant bias: rounded once so float noise can never reorder
        # plans; exactly 0.0 for every box when tenancy is off
        bias = round(
            sum(over.get(w.tenant, 0.0) for w in victims.values()), 9
        )
        key = (
            cost,
            -bias,  # more over-share victims = preferred at equal cost
            len(victims),
            sb.surface,
            sb.contact,  # already negated: lower = snugger
            sb.origin_key,
        )
        if best is None or key < best[0]:
            best = (key, cost, coords,
                    [victims[i] for i in sorted(victims)])
    if best is None:
        return None
    _, cost, coords, victims = best
    return PreemptionPlan(
        coords=coords,
        victims=victims,
        cost_priority_sum=cost,
        victim_count=len(victims),
    )
